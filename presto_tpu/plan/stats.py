"""Statistics framework: per-column stats + derivation through plan nodes.

Re-designed equivalent of the reference's cost framework
(presto-main/.../cost/, 40 files: StatsCalculator.java,
FilterStatsCalculator.java, JoinStatsRule.java, and the connector stats
SPI feeding it). TPU-first reduction: ONE derivation function over the
frozen plan dataclasses producing `PlanStats` — estimated row count plus
per-channel `ColumnStats` (NDV / min / max / null fraction) — memoized per
walk. Consumers:

* the planner's join ordering (sql/planner.py FromPlanner picks the next
  relation by estimated JOIN OUTPUT, reference ReorderJoins),
* the fragmenter's broadcast-vs-repartition choice
  (plan/fragment.py, reference DetermineJoinDistributionType),
* EXPLAIN row estimates.

min/max are LOGICAL values (days for dates, unscaled-decimal / 10^scale,
None for varchar) so they compare directly against literal values.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

from .. import types as T
from ..expr import ir
from . import nodes as N

DEFAULT_FILTER_SELECTIVITY = 0.25
DEFAULT_EQ_SELECTIVITY = 0.05
DEFAULT_RANGE_SELECTIVITY = 0.35


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Reference: spi/statistics/ColumnStatistics (+ the equi-depth
    histogram the reference derives in FilterStatsCalculator via
    StatisticRange — here carried explicitly)."""

    ndv: Optional[float] = None
    min: Optional[float] = None  # logical value; None = unknown/varchar
    max: Optional[float] = None
    null_fraction: float = 0.0
    # equi-depth boundaries (logical values at quantiles 0..1): rank of a
    # value interpolates to a selectivity without a uniformity assumption
    histogram: Optional[Tuple[float, ...]] = None

    def fraction_below(self, x: float) -> Optional[float]:
        """P[col <= x] over non-null rows, from the histogram when
        present, else linear between min/max."""
        h = self.histogram
        if h and len(h) >= 2:
            import bisect

            b = len(h) - 1
            i = bisect.bisect_right(h, x)
            if i == 0:
                return 0.0
            if i > b:
                return 1.0
            lo, hi = h[i - 1], h[i]
            inner = 0.0 if hi <= lo else (x - lo) / (hi - lo)
            return ((i - 1) + min(max(inner, 0.0), 1.0)) / b
        if self.min is None or self.max is None or self.max <= self.min:
            return None
        return min(max((x - self.min) / (self.max - self.min), 0.0), 1.0)

    def cap_ndv(self, rows: float) -> "ColumnStats":
        if self.ndv is None or self.ndv <= rows:
            return self
        return dataclasses.replace(self, ndv=max(rows, 1.0))


@dataclasses.dataclass(frozen=True)
class PlanStats:
    """Reference: cost/PlanNodeStatsEstimate."""

    rows: float
    columns: Dict[str, ColumnStats] = dataclasses.field(default_factory=dict)

    def column(self, ch: str) -> ColumnStats:
        return self.columns.get(ch, ColumnStats())

    def scaled(self, factor: float) -> "PlanStats":
        rows = max(self.rows * factor, 0.0)
        return PlanStats(
            rows, {c: s.cap_ndv(rows) for c, s in self.columns.items()}
        )


def literal_value(lit: ir.Literal) -> Optional[float]:
    """Logical ordering value of a literal (matches ColumnStats min/max)."""
    v = lit.value
    if v is None:
        return None
    t = lit.type
    if isinstance(t, T.DateType):
        if isinstance(v, str):
            import datetime as dt

            try:
                d = dt.date.fromisoformat(v)
            except ValueError:
                return None
            return float((d - dt.date(1970, 1, 1)).days)
        return float(v)
    if isinstance(t, T.VarcharType):
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class StatsDeriver:
    """One memoized derivation walk (reference StatsCalculator's rule set,
    collapsed into a visitor).

    With history-based feedback on (plan/history.py, PRESTO_TPU_FEEDBACK
    + the adaptive_plan breaker), every derived estimate is overridden by
    a validated OBSERVED row count for the node's semantic frame before
    it is memoized — so join reordering, build/probe-side selection and
    the fragmenter's broadcast switch all run on measured rows. Pass
    use_history=False to force the static derivation (the breaker's
    fallback, and the baseline the error surfaces compare against)."""

    def __init__(self, catalog, use_history: Optional[bool] = None):
        self.catalog = catalog
        self._memo: Dict[int, PlanStats] = {}
        self._fp_memo: Dict[int, tuple] = {}
        self._history = None
        if use_history is not False:
            try:
                from . import history as H

                if use_history or H.feedback_on():
                    self._history = H.HISTORY
            except Exception:  # noqa: BLE001 — feedback is best-effort
                self._history = None

    def stats(self, node: N.PlanNode) -> PlanStats:
        got = self._memo.get(id(node))
        if got is None:
            got = self._derive(node)
            if self._history is not None:
                got = self._observed(node, got)
            self._memo[id(node)] = got
        return got

    def _observed(self, node: N.PlanNode, ps: PlanStats) -> PlanStats:
        """Replace the estimated row count with the store's observation
        when one is live for this node's frame; column stats stay derived
        (history records counts, not distributions) with NDVs re-capped.
        Any store fault trips the adaptive_plan breaker and reverts this
        walk to static derivation."""
        try:
            from . import history as H

            fp = H.fingerprint(node, self._fp_memo)
            obs = H.HISTORY.observed_rows(fp, self.catalog)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            from ..exec.breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))
            self._history = None
            return ps
        if obs is None:
            return ps
        return PlanStats(
            obs, {c: s.cap_ndv(obs) for c, s in ps.columns.items()}
        )

    # -- per-node rules --

    def _derive(self, node: N.PlanNode) -> PlanStats:
        meth = getattr(self, f"_d_{type(node).__name__.lower()}", None)
        if meth is not None:
            return meth(node)
        if node.children:
            return self.stats(node.children[0])
        return PlanStats(1e6)

    def _d_tablescan(self, node: N.TableScan) -> PlanStats:
        try:
            rows = float(self.catalog.row_count(node.table))
        except Exception:
            return PlanStats(1e9)
        cols: Dict[str, ColumnStats] = {}
        get = getattr(self.catalog, "column_stats", None)
        if get is not None:
            for ch, src, _typ in node.columns:
                try:
                    cs = get(node.table, src)
                except Exception:  # noqa: BLE001 — connector stats are
                    # best-effort: a missing column simply has no stats
                    cs = None
                if cs is not None:
                    cols[ch] = cs
        return PlanStats(rows, cols)

    def _d_singlerow(self, node) -> PlanStats:
        return PlanStats(1.0)

    def _d_sample(self, node) -> PlanStats:
        child = self.stats(node.children[0])
        return dataclasses.replace(
            child, rows=max(child.rows * node.fraction, 1.0)
        )

    def _d_filter(self, node: N.Filter) -> PlanStats:
        return filter_stats(self.stats(node.child), node.predicate)

    def _d_project(self, node: N.Project) -> PlanStats:
        child = self.stats(node.child)
        cols = {}
        for nm, e in zip(node.names, node.exprs):
            if isinstance(e, ir.ColumnRef):
                cols[nm] = child.column(e.name)
        return PlanStats(child.rows, cols)

    def _d_aggregate(self, node: N.Aggregate) -> PlanStats:
        child = self.stats(node.child)
        if not node.group_exprs:
            return PlanStats(1.0)
        groups = 1.0
        cols = {}
        for nm, e in zip(node.group_names, node.group_exprs):
            cs = (
                child.column(e.name)
                if isinstance(e, ir.ColumnRef)
                else ColumnStats()
            )
            cols[nm] = cs
            groups *= cs.ndv if cs.ndv else max(child.rows / 10.0, 1.0)
            groups = min(groups, child.rows)
        rows = max(min(groups, child.rows), 1.0)
        return PlanStats(rows, {c: s.cap_ndv(rows) for c, s in cols.items()})

    def _d_distinct(self, node: N.Distinct) -> PlanStats:
        child = self.stats(node.child)
        groups = 1.0
        for f, _t in node.fields:
            cs = child.column(f)
            groups *= cs.ndv if cs.ndv else max(child.rows / 10.0, 1.0)
            groups = min(groups, child.rows)
        return PlanStats(max(groups, 1.0), dict(child.columns))

    def _d_join(self, node: N.Join) -> PlanStats:
        left, right = self.stats(node.left), self.stats(node.right)
        rows = join_output_rows(
            left, right, node.left_keys, node.right_keys, node.kind
        )
        cols = {**left.columns, **right.columns}
        return PlanStats(rows, {c: s.cap_ndv(rows) for c, s in cols.items()})

    def _d_semijoin(self, node: N.SemiJoin) -> PlanStats:
        child, source = self.stats(node.child), self.stats(node.source)
        if node.mark is not None:
            # mark joins filter NOTHING: every probe row passes through
            # plus a boolean membership column
            return PlanStats(child.rows, dict(child.columns))
        sel = 0.5
        if node.probe_keys and isinstance(node.probe_keys[0], ir.ColumnRef):
            pk = child.column(node.probe_keys[0].name)
            sk = (
                source.column(node.source_keys[0].name)
                if node.source_keys and isinstance(node.source_keys[0], ir.ColumnRef)
                else ColumnStats()
            )
            if pk.ndv and sk.ndv:
                sel = min(sk.ndv / pk.ndv, 1.0)
        if node.anti:
            sel = 1.0 - sel
        return child.scaled(max(sel, 0.01))

    def _d_union(self, node: N.Union) -> PlanStats:
        rows = sum(self.stats(c).rows for c in node.children)
        return PlanStats(max(rows, 1.0), dict(self.stats(node.children[0]).columns))

    def _d_limit(self, node: N.Limit) -> PlanStats:
        child = self.stats(node.child)
        return PlanStats(min(child.rows, float(node.count)), dict(child.columns))

    def _d_topn(self, node: N.TopN) -> PlanStats:
        child = self.stats(node.child)
        return PlanStats(min(child.rows, float(node.count)), dict(child.columns))

    def _d_unnest(self, node: N.Unnest) -> PlanStats:
        return self.stats(node.child).scaled(3.0)


def filter_stats(child: PlanStats, predicate) -> PlanStats:
    """FilterStatsCalculator: per-conjunct selectivity from column stats,
    narrowing the filtered column's range/NDV."""
    rows = child.rows
    cols = dict(child.columns)

    def conjuncts(e):
        if isinstance(e, ir.Call) and e.name == "and":
            for a in e.args:
                yield from conjuncts(a)
        else:
            yield e

    sel_total = 1.0
    for e in conjuncts(predicate):
        s = _conjunct_selectivity(e, cols)
        sel_total *= s
    rows = max(rows * sel_total, 0.0)
    return PlanStats(rows, {c: cs.cap_ndv(rows) for c, cs in cols.items()})


def _conjunct_selectivity(e, cols: Dict[str, ColumnStats]) -> float:
    if not isinstance(e, ir.Call):
        return 0.5
    if e.name == "or":
        s = 0.0
        for a in e.args:
            s = s + _conjunct_selectivity(a, dict(cols)) * (1 - s)
        return min(s, 1.0)
    if e.name == "not" and len(e.args) == 1:
        return 1.0 - _conjunct_selectivity(e.args[0], dict(cols))
    col, lit, op = _col_op_literal(e)
    if col is None:
        from ..sql.planner import _selectivity

        return _selectivity(e)
    cs = cols.get(col.name, ColumnStats())
    nn = 1.0 - cs.null_fraction
    if op == "eq":
        if lit is None:
            return DEFAULT_EQ_SELECTIVITY
        cols[col.name] = dataclasses.replace(
            cs, ndv=1.0, min=lit, max=lit, null_fraction=0.0
        )
        if cs.ndv:
            return nn / cs.ndv
        return DEFAULT_EQ_SELECTIVITY
    if op == "in":
        k = len(e.args) - 1
        if cs.ndv:
            return min(nn * k / cs.ndv, 1.0)
        return min(0.05 * k, 0.5)
    if op in ("lt", "le", "gt", "ge", "between"):
        if (
            lit is None
            or cs.min is None
            or cs.max is None
            or cs.max <= cs.min
        ):
            return DEFAULT_RANGE_SELECTIVITY
        # histogram-aware rank interpolation (reference
        # FilterStatsCalculator range estimation; equi-depth histogram
        # replaces the uniformity assumption where the sample derived
        # one). Fractions are CONDITIONED on the current [min, max] —
        # earlier conjuncts narrow min/max but keep the full-table
        # histogram, so renormalize to the surviving mass.
        f_lo = cs.fraction_below(cs.min) or 0.0
        f_hi = cs.fraction_below(cs.max)
        f_hi = 1.0 if f_hi is None else f_hi
        mass = max(f_hi - f_lo, 1e-12)

        def cond_below(x: float) -> float:
            f = cs.fraction_below(min(max(x, cs.min), cs.max))
            if f is None:
                return DEFAULT_RANGE_SELECTIVITY
            return min(max((f - f_lo) / mass, 0.0), 1.0)

        if op == "between":
            lo, hi = lit
            frac = max(cond_below(hi) - cond_below(lo), 0.0)
            cols[col.name] = dataclasses.replace(
                cs, min=max(lo, cs.min), max=min(hi, cs.max)
            )
        elif op in ("lt", "le"):
            frac = cond_below(lit)
            cols[col.name] = dataclasses.replace(cs, max=min(lit, cs.max))
        else:
            frac = 1.0 - cond_below(lit)
            cols[col.name] = dataclasses.replace(cs, min=max(lit, cs.min))
        return nn * min(max(frac, 0.0), 1.0)
    if op == "like":
        return 0.1
    return 0.5


def _col_op_literal(e: ir.Call):
    """Match (column op literal) in either direction; returns
    (ColumnRef|None, logical value, op). BETWEEN returns a (lo, hi) pair."""
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    if e.name == "between" and len(e.args) == 3:
        c, lo, hi = e.args
        if (
            isinstance(c, ir.ColumnRef)
            and isinstance(lo, ir.Literal)
            and isinstance(hi, ir.Literal)
        ):
            vlo, vhi = literal_value(lo), literal_value(hi)
            if vlo is not None and vhi is not None:
                return c, (vlo, vhi), "between"
        return None, None, None
    if e.name == "in":
        if e.args and isinstance(e.args[0], ir.ColumnRef):
            return e.args[0], None, "in"
        return None, None, None
    if e.name == "like" and isinstance(e.args[0], ir.ColumnRef):
        return e.args[0], None, "like"
    if e.name not in flip or len(e.args) != 2:
        return None, None, None
    a, b = e.args
    if isinstance(a, ir.ColumnRef) and isinstance(b, ir.Literal):
        return a, literal_value(b), e.name
    if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Literal):
        return b, literal_value(a), flip[e.name]
    return None, None, None


def join_output_rows(
    left: PlanStats, right: PlanStats, left_keys, right_keys, kind: str
) -> float:
    """JoinStatsRule: |L x R| / prod(max(ndv_l, ndv_r)) per key pair
    (independence assumption), floored for outer kinds."""
    if not left_keys:
        rows = left.rows * right.rows  # cross join
    else:
        rows = left.rows * right.rows
        for lk, rk in zip(left_keys, right_keys):
            nl = (
                left.column(lk.name).ndv
                if isinstance(lk, ir.ColumnRef)
                else None
            )
            nr = (
                right.column(rk.name).ndv
                if isinstance(rk, ir.ColumnRef)
                else None
            )
            d = max(nl or 0.0, nr or 0.0)
            if d <= 0:
                d = max(min(left.rows, right.rows) / 10.0, 1.0)
            rows /= d
    rows = max(rows, 1.0)
    if kind == "left":
        rows = max(rows, left.rows)
    elif kind == "right":
        rows = max(rows, right.rows)
    elif kind == "full":
        rows = max(rows, left.rows + right.rows)
    return rows


def derive(node: N.PlanNode, catalog) -> PlanStats:
    """Entry point: stats for one plan tree (memoized within the call)."""
    return StatsDeriver(catalog).stats(node)


def storage_bounds(cs: ColumnStats, typ):
    """[lo, hi] in STORAGE units from a column's LOGICAL min/max stats —
    the keypack planner's input (ops/keypack.py). ColumnStats min/max are
    logical (scaled decimals divided out, dates as epoch days); bit
    packing operates on storage integers, so scale is multiplied back in
    with a +-1 margin against float rounding. Floats are returned as
    float bounds (the planner transforms them through the total-order
    map). None = unknown / unbounded — the column can't be
    stats-packed."""
    import math

    if cs is None or cs.min is None or cs.max is None:
        return None
    lo_f, hi_f = float(cs.min), float(cs.max)
    if not (math.isfinite(lo_f) and math.isfinite(hi_f)) or hi_f < lo_f:
        return None
    if isinstance(typ, (T.DoubleType, T.RealType)):
        return lo_f, hi_f
    scale = getattr(typ, "scale", 0) or 0
    mul = 10 ** scale
    return math.floor(lo_f * mul) - 1, math.ceil(hi_f * mul) + 1


def stats_from_column(
    data, valid, typ, dictionary, total_rows: int
) -> ColumnStats:
    """Compute ColumnStats from a (possibly sampled) host column. NDV
    scales up linearly when the sample looks key-like (>50% distinct) —
    the standard low/high-cardinality split. min/max are LOGICAL values
    (scaled decimals divided out, dates as epoch days); varchar columns
    get NDV only."""
    import numpy as np

    data = np.asarray(data)
    n = len(data)
    null_fraction = 0.0
    if valid is not None:
        valid = np.asarray(valid)
        null_fraction = float(1.0 - valid.mean()) if n else 0.0
        data = data[valid]
    if data.size == 0:
        return ColumnStats(ndv=0.0, null_fraction=null_fraction)
    if data.ndim == 2:  # long-decimal lanes: logical = hi*2^32 + lo
        data = data[:, 0].astype(np.float64) * float(1 << 32) + data[
            :, 1
        ].astype(np.float64)
    d = float(len(np.unique(data)))
    if dictionary is not None:
        return ColumnStats(
            ndv=min(d, float(len(dictionary))), null_fraction=null_fraction
        )
    ndv = d
    if total_rows > n and d / max(len(data), 1) > 0.5:
        ndv = d * (total_rows / n)
    scale = getattr(typ, "scale", None)
    div = float(10**scale) if scale else 1.0
    hist = None
    if data.size >= 64:
        # 32-bucket equi-depth boundaries from the sample (reference:
        # the StatisticRange-based estimates FilterStatsCalculator makes;
        # an explicit histogram replaces the uniformity assumption)
        qs = np.quantile(data, np.linspace(0.0, 1.0, 33))
        hist = tuple(float(q) / div for q in qs)
    return ColumnStats(
        ndv=ndv,
        min=float(data.min()) / div,
        max=float(data.max()) / div,
        null_fraction=null_fraction,
        histogram=hist,
    )
