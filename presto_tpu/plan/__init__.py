"""Logical plan: nodes + planner output.

Equivalent of the reference's sql/planner PlanNode vocabulary
(presto-main/.../sql/planner/plan/ — TableScanNode, FilterNode, ProjectNode,
AggregationNode, JoinNode, SemiJoinNode, SortNode, TopNNode, LimitNode,
ExchangeNode ...). Nodes are frozen dataclasses with typed output schemas;
every node maps onto one kernel-library call (ops/) or a mesh exchange
(parallel/).
"""

from .nodes import *  # noqa: F401,F403
