"""Plan fragmentation: exchange placement + partitioning vocabulary.

Re-designed equivalent of the reference's distribution planning:
AddExchanges (presto-main/.../sql/planner/optimizations/AddExchanges.java)
decides where data must be repartitioned/replicated/gathered, and
PlanFragmenter (sql/planner/PlanFragmenter.java) cuts the plan at exchange
boundaries. The partitioning vocabulary mirrors SystemPartitioningHandle
(sql/planner/SystemPartitioningHandle.java:57-65):

  SOURCE      arbitrary row shards across workers (leaf scans / splits)
  HASH        rows co-located by hash of a key set (FIXED_HASH_DISTRIBUTION)
  SINGLE      all rows on one logical worker (SINGLE_DISTRIBUTION)
  REPLICATED  a full copy on every worker (FIXED_BROADCAST_DISTRIBUTION)

TPU-first reductions vs the reference:
* Exchanges are collectives over the device mesh, not HTTP shuffles —
  `repartition` lowers to shuffle_write + lax.all_to_all, `replicate` /
  `gather` to device-global compaction (XLA inserts the all_gather).
* Fragments are not separately scheduled task groups: the distributed
  executor walks ONE physical tree and switches between sharded shard_map
  stages and single-device execution at Exchange nodes. `fragments()`
  recovers the reference-style fragment list for EXPLAIN.
* Aggregations split into partial/final around the exchange exactly like the
  reference's AggregationNode.Step (partial pre-exchange, final post-
  exchange, avg recomposed from sum/count afterwards).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from .. import types as T
from ..expr import ir
from ..ops.aggregate import decompose_partial
from . import nodes as N

# partitioning kinds
SOURCE = "source"
HASH = "hash"
SINGLE = "single"
REPLICATED = "replicated"


@dataclasses.dataclass(frozen=True)
class Partitioning:
    """Distribution of a node's output across the worker mesh axis."""

    kind: str  # SOURCE | HASH | SINGLE | REPLICATED
    keys: Tuple[ir.RowExpression, ...] = ()

    @property
    def is_sharded(self) -> bool:
        return self.kind in (SOURCE, HASH)


@dataclasses.dataclass(frozen=True)
class Exchange(N.PlanNode):
    """Data movement between distributions (reference ExchangeNode with
    scope=REMOTE). kind: repartition (hash all_to_all) | replicate
    (broadcast full copy) | gather (collect to SINGLE)."""

    child: N.PlanNode
    kind: str  # 'repartition' | 'replicate' | 'gather'
    keys: Tuple[ir.RowExpression, ...] = ()

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class AggFinalize(N.PlanNode):
    """Post-final-aggregation step recomposing user-visible aggregates from
    decomposed partial columns (avg = sum/count). Output schema equals the
    original Aggregate node's."""

    child: N.PlanNode
    group_fields: Tuple[N.Field, ...]
    aggs: Tuple[object, ...]  # original AggSpecs
    post: Tuple[object, ...]  # AvgPost steps

    @property
    def fields(self):
        return self.group_fields + tuple(
            (a.name, a.output_type) for a in self.aggs
        )

    @property
    def children(self):
        return (self.child,)


class Fragmenter:
    """Insert exchanges bottom-up so every operator's co-location
    requirement is met; track each subtree's delivered Partitioning.

    broadcast_threshold=None selects COST-BASED join distribution
    (reference DetermineJoinDistributionType): broadcast replicates the
    build side onto every worker (build_rows x W moved, probe stays put);
    repartition moves both sides once. An explicit integer keeps the
    legacy fixed row cutover."""

    def __init__(
        self,
        catalog,
        broadcast_threshold: Optional[int] = None,
        num_workers: int = 8,
    ):
        self.catalog = catalog
        self.broadcast_threshold = broadcast_threshold
        self.num_workers = max(num_workers, 2)
        from .stats import StatsDeriver

        self._stats = StatsDeriver(catalog)

    def fragment(self, root: N.PlanNode) -> N.PlanNode:
        node, dist = self._visit(root)
        if dist.is_sharded:
            node = Exchange(node, "gather")
        return node

    # -- helpers --

    def _estimate(self, node: N.PlanNode) -> float:
        return self._stats.stats(node).rows

    def _should_broadcast(self, build: N.PlanNode, probe: N.PlanNode) -> bool:
        build_rows = self._estimate(build)
        if self.broadcast_threshold is not None:
            return build_rows <= self.broadcast_threshold
        probe_rows = self._estimate(probe)
        # replicate cost: every worker holds the build (W x build moved);
        # repartition cost: both sides cross the exchange once
        return build_rows * self.num_workers <= probe_rows + build_rows

    def _gather(self, node: N.PlanNode, dist: Partitioning) -> N.PlanNode:
        return Exchange(node, "gather") if dist.is_sharded else node

    @staticmethod
    def _has_varchar_keys(keys) -> bool:
        return any(isinstance(k.type, T.VarcharType) for k in keys)

    # -- dispatch --

    def _visit(self, node: N.PlanNode) -> Tuple[N.PlanNode, Partitioning]:
        m = getattr(self, f"_v_{type(node).__name__.lower()}", None)
        if m is None:
            raise NotImplementedError(
                f"fragmenter: unhandled node {type(node).__name__}"
            )
        return m(node)

    def _v_tablescan(self, node):
        return node, Partitioning(SOURCE)

    def _v_singlerow(self, node):
        return node, Partitioning(SINGLE)

    def _v_unnest(self, node):
        # row-local expansion: runs on whatever distribution the child has
        child, dist = self._visit(node.child)
        return dataclasses.replace(node, child=child), dist

    def _v_sample(self, node):
        child, dist = self._visit(node.child)
        return dataclasses.replace(node, child=child), dist

    def _v_filter(self, node):
        child, dist = self._visit(node.child)
        # dataclasses.replace keeps the dynamic-filter consumer annotation
        return dataclasses.replace(node, child=child), dist

    def _v_project(self, node):
        child, dist = self._visit(node.child)
        return N.Project(child, node.exprs, node.names), dist

    def _v_output(self, node):
        child, dist = self._visit(node.child)
        child = self._gather(child, dist)
        return N.Output(child, node.channels, node.titles), Partitioning(SINGLE)

    def _v_aggregate(self, node: N.Aggregate):
        child, dist = self._visit(node.child)
        if not dist.is_sharded:
            return (
                N.Aggregate(
                    child, node.group_exprs, node.group_names, node.aggs,
                    node.mask,
                ),
                Partitioning(SINGLE),
            )
        try:
            partial_specs, final_specs, post = decompose_partial(node.aggs)
        except KeyError:
            # non-decomposable aggregate: gather and aggregate on one worker
            child = self._gather(child, dist)
            return (
                N.Aggregate(
                    child, node.group_exprs, node.group_names, node.aggs,
                    node.mask,
                ),
                Partitioning(SINGLE),
            )
        # the fused selection mask applies to the PARTIAL step only — final
        # aggregation combines already-masked partial rows
        partial = N.Aggregate(
            child, node.group_exprs, node.group_names, partial_specs,
            node.mask,
        )
        key_refs = tuple(
            ir.ColumnRef(nm, e.type)
            for nm, e in zip(node.group_names, node.group_exprs)
        )
        group_fields = tuple(
            (nm, e.type) for nm, e in zip(node.group_names, node.group_exprs)
        )
        if not node.group_exprs:
            # global aggregation: one partial row per shard, gather, finalize
            exch = Exchange(partial, "gather")
            final = N.Aggregate(exch, (), (), final_specs)
            return (
                AggFinalize(final, (), node.aggs, post),
                Partitioning(SINGLE),
            )
        exch = Exchange(partial, "repartition", key_refs)
        final = N.Aggregate(exch, key_refs, node.group_names, final_specs)
        return (
            AggFinalize(final, group_fields, node.aggs, post),
            Partitioning(HASH, key_refs),
        )

    def _v_join(self, node: N.Join):
        left, ldist = self._visit(node.left)
        right, rdist = self._visit(node.right)
        if node.kind == "full" or (
            node.kind != "inner" and node.residual is not None
        ):
            # multi-kernel outer composition (Executor._exec_outer_join)
            # runs single-node: null-extension of the build side cannot be
            # decided per shard under replication
            left = self._gather(left, ldist)
            right = self._gather(right, rdist)
            return (
                dataclasses.replace(node, left=left, right=right),
                Partitioning(SINGLE),
            )
        if not ldist.is_sharded and not rdist.is_sharded:
            return (
                dataclasses.replace(node, left=left, right=right),
                Partitioning(SINGLE),
            )
        if not ldist.is_sharded:
            # probe single: gather the build side too (small probe side means
            # no distribution to preserve)
            right = self._gather(right, rdist)
            return (
                dataclasses.replace(node, left=left, right=right),
                Partitioning(SINGLE),
            )
        broadcast = (
            self._should_broadcast(node.right, node.left)
            or not node.left_keys
            or self._has_varchar_keys(node.left_keys)
            or self._has_varchar_keys(node.right_keys)
        )
        if broadcast:
            # replicate the build side on every worker; probe stays put
            # (reference DetermineJoinDistributionType -> REPLICATED)
            right = Exchange(self._gather(right, rdist), "replicate")
            return dataclasses.replace(node, left=left, right=right), ldist
        # repartition both sides on the join keys (-> PARTITIONED)
        left = Exchange(left, "repartition", node.left_keys)
        right = Exchange(right, "repartition", node.right_keys)
        return (
            dataclasses.replace(node, left=left, right=right),
            Partitioning(HASH, node.left_keys),
        )

    def _v_semijoin(self, node: N.SemiJoin):
        child, cdist = self._visit(node.child)
        source, sdist = self._visit(node.source)
        if not cdist.is_sharded:
            source = self._gather(source, sdist)
            return (
                dataclasses.replace(node, child=child, source=source),
                Partitioning(SINGLE),
            )
        broadcast = (
            self._should_broadcast(node.source, node.child)
            or not node.probe_keys
            or node.residual is not None
            or self._has_varchar_keys(node.probe_keys)
            or self._has_varchar_keys(node.source_keys)
        )
        if broadcast:
            source = Exchange(self._gather(source, sdist), "replicate")
            return (
                dataclasses.replace(node, child=child, source=source),
                cdist,
            )
        child = Exchange(child, "repartition", node.probe_keys)
        source = Exchange(source, "repartition", node.source_keys)
        return (
            dataclasses.replace(node, child=child, source=source),
            Partitioning(HASH, node.probe_keys),
        )

    def _v_scalarapply(self, node: N.ScalarApply):
        child, cdist = self._visit(node.child)
        sub, sdist = self._visit(node.subquery)
        sub = self._gather(sub, sdist)
        return (
            dataclasses.replace(node, child=child, subquery=sub),
            cdist,
        )

    def _v_window(self, node: N.Window):
        child, dist = self._visit(node.child)
        if not dist.is_sharded:
            return dataclasses.replace(node, child=child), Partitioning(SINGLE)
        if not node.partition_exprs:
            child = self._gather(child, dist)
            return dataclasses.replace(node, child=child), Partitioning(SINGLE)
        child = Exchange(child, "repartition", node.partition_exprs)
        return (
            dataclasses.replace(node, child=child),
            Partitioning(HASH, node.partition_exprs),
        )

    def _v_sort(self, node: N.Sort):
        child, dist = self._visit(node.child)
        child = self._gather(child, dist)
        return N.Sort(child, node.keys), Partitioning(SINGLE)

    def _v_topn(self, node: N.TopN):
        child, dist = self._visit(node.child)
        if dist.is_sharded:
            # per-shard top-N is a superset of the global top-N
            child = Exchange(N.TopN(child, node.keys, node.count), "gather")
        return N.TopN(child, node.keys, node.count), Partitioning(SINGLE)

    def _v_limit(self, node: N.Limit):
        child, dist = self._visit(node.child)
        if dist.is_sharded:
            child = Exchange(N.Limit(child, node.count), "gather")
        return N.Limit(child, node.count), Partitioning(SINGLE)

    def _v_distinct(self, node: N.Distinct):
        child, dist = self._visit(node.child)
        if not dist.is_sharded:
            return N.Distinct(child), Partitioning(SINGLE)
        keys = tuple(ir.ColumnRef(nm, t) for nm, t in child.fields)
        if self._has_varchar_keys(keys):
            child = self._gather(child, dist)
            return N.Distinct(child), Partitioning(SINGLE)
        # local pre-distinct shrinks the exchange (reference partial distinct)
        child = Exchange(N.Distinct(child), "repartition", keys)
        return N.Distinct(child), Partitioning(HASH, keys)

    def _v_union(self, node: N.Union):
        inputs = []
        for c in node.inputs:
            cn, cd = self._visit(c)
            inputs.append(self._gather(cn, cd))
        return (
            N.Union(tuple(inputs), node.distinct),
            Partitioning(SINGLE),
        )


def fragment_plan(
    root: N.PlanNode,
    catalog,
    broadcast_threshold: Optional[int] = None,
    num_workers: int = 8,
) -> N.PlanNode:
    """AddExchanges + fragmentation entry point. broadcast_threshold=None
    = cost-based distribution from the stats framework."""
    return Fragmenter(catalog, broadcast_threshold, num_workers).fragment(root)


def fragments(root: N.PlanNode) -> List[N.PlanNode]:
    """Cut the physical plan at Exchange boundaries into reference-style
    fragments (roots listed top-down; fragment 0 is the SINGLE root)."""
    out: List[N.PlanNode] = [root]
    stack = [root]
    while stack:
        n = stack.pop()
        for c in n.children:
            if isinstance(c, Exchange):
                out.append(c.child)
                stack.append(c.child)
            else:
                stack.append(c)
    return out
