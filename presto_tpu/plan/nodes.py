"""Logical plan nodes.

Mirrors the reference's plan-node vocabulary (presto-main/.../sql/planner/
plan/) with TPU-relevant reductions: expressions are already-typed
RowExpressions (expr/ir.py), and every node carries its output schema as
(channel_name, Type) pairs. Channel names are globally unique per planning
session (the analog of the reference's Symbol allocator,
sql/planner/SymbolAllocator.java), so joins can concatenate columns without
collisions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from .. import types as T
from ..expr.ir import RowExpression
from ..ops.aggregate import AggSpec
from ..ops.sort import SortKey

Field = Tuple[str, T.Type]  # (channel name, type)


@dataclasses.dataclass(frozen=True)
class PlanNode:
    @property
    def fields(self) -> Tuple[Field, ...]:
        raise NotImplementedError

    @property
    def children(self) -> Tuple["PlanNode", ...]:
        return ()

    def field_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.fields)

    def field_type(self, name: str) -> T.Type:
        for n, t in self.fields:
            if n == name:
                return t
        raise KeyError(name)


@dataclasses.dataclass(frozen=True)
class TableScan(PlanNode):
    """Scan of a connector table (reference TableScanNode). `columns` maps
    output channel -> source column name."""

    catalog: str
    table: str
    columns: Tuple[Tuple[str, str, T.Type], ...]  # (channel, source col, type)
    # runtime dynamic-filter consumers (plan/rules.annotate_dynamic_filters):
    # (filter_id, channel, source column, apply_mask). apply_mask=False
    # means a Filter above this scan applies the device mask (fused into
    # its compaction) and the scan only forwards SPI pruning hints.
    dynamic_filters: Tuple[Tuple[str, str, str, bool], ...] = ()

    @property
    def fields(self):
        return tuple((c, t) for c, _, t in self.columns)


@dataclasses.dataclass(frozen=True)
class Sample(PlanNode):
    """TABLESAMPLE BERNOULLI/SYSTEM(p) (reference SampleNode; both
    sample types execute as row-level bernoulli here — SYSTEM's
    split-level granularity has no analog when a scan is one device
    array). Seeded at plan time so each query samples differently but
    one query's plan is deterministic under kernel caching."""

    child: PlanNode
    fraction: float  # 0..1
    seed: int

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Unnest(PlanNode):
    """Expand array expressions into rows: child columns replicate per
    element, arrays zip by position (reference UnnestNode +
    operator/UnnestOperator.java). One element channel per array, plus an
    optional 1-based ordinality channel."""

    child: PlanNode
    array_exprs: Tuple[RowExpression, ...]
    elem_channels: Tuple[str, ...]
    ordinality_channel: Optional[str] = None

    @property
    def children(self):
        return (self.child,)

    @property
    def fields(self):
        out = list(self.child.fields)
        for e, ch in zip(self.array_exprs, self.elem_channels):
            out.append((ch, e.type.element))
        if self.ordinality_channel is not None:
            out.append((self.ordinality_channel, T.BIGINT))
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class SingleRow(PlanNode):
    """Leaf producing exactly one row with a single dummy column. VALUES
    rows are planned as Project(SingleRow) per row, unioned (reference
    ValuesNode, sql/planner/plan/ValuesNode.java — re-designed so literal
    rows flow through the same expression compiler as every projection)."""

    channel: str

    @property
    def fields(self):
        return ((self.channel, T.BIGINT),)


@dataclasses.dataclass(frozen=True)
class Filter(PlanNode):
    child: PlanNode
    predicate: RowExpression
    # dynamic-filter consumers fused into this filter's keep mask:
    # (filter_id, channel) — pruning shares the predicate's one compaction
    dynamic_filters: Tuple[Tuple[str, str], ...] = ()

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Project(PlanNode):
    child: PlanNode
    exprs: Tuple[RowExpression, ...]
    names: Tuple[str, ...]

    @property
    def fields(self):
        return tuple((n, e.type) for n, e in zip(self.names, self.exprs))

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Aggregate(PlanNode):
    """Grouped aggregation (reference AggregationNode). Empty group_exprs =
    global aggregation (one output row)."""

    child: PlanNode
    group_exprs: Tuple[RowExpression, ...]
    group_names: Tuple[str, ...]
    aggs: Tuple[AggSpec, ...]
    # fused selection: rows failing `mask` don't contribute and don't form
    # groups — the executor-level fusion of Filter into aggregation (on TPU
    # the filter's compaction costs more than masked reductions; see
    # optimizer.fuse_filter_into_aggregates)
    mask: Optional[RowExpression] = None

    @property
    def fields(self):
        out = tuple(
            (n, e.type) for n, e in zip(self.group_names, self.group_exprs)
        )
        return out + tuple((a.name, a.output_type) for a in self.aggs)

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Join(PlanNode):
    """Equi-join with optional residual filter (reference JoinNode).

    kind: inner | left. Output = left fields then right fields (for `left`
    joins the right side's values are NULL on no match)."""

    kind: str
    left: PlanNode
    right: PlanNode
    left_keys: Tuple[RowExpression, ...]
    right_keys: Tuple[RowExpression, ...]
    residual: Optional[RowExpression] = None  # over combined channels
    unique_build: bool = False  # planner knows build keys are unique (n:1)
    # dynamic filters PRODUCED from this join's build side after it
    # materializes: (filter_id, build key index, has_scan_consumer). With
    # no scan consumer the executor applies the filter as an on-device
    # pre-probe mask instead (inner joins only).
    dynamic_filters: Tuple[Tuple[str, int, bool], ...] = ()

    @property
    def fields(self):
        return self.left.fields + self.right.fields

    @property
    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class SemiJoin(PlanNode):
    """EXISTS/IN-subquery join (reference SemiJoinNode): keeps probe rows
    with (anti: without) a match in `source`. Residual (for correlated
    EXISTS with extra predicates) references both sides' channels.

    With `mark` set, NO rows are filtered: every probe row passes through
    plus a boolean `mark` column recording match membership (the
    reference's semi-join output symbol, HashSemiJoinOperator) — how
    EXISTS/IN under OR plans."""

    child: PlanNode
    source: PlanNode
    probe_keys: Tuple[RowExpression, ...]
    source_keys: Tuple[RowExpression, ...]
    anti: bool = False
    residual: Optional[RowExpression] = None
    mark: Optional[str] = None
    # dynamic filters produced from `source` (plain semi joins only —
    # anti/mark keep or annotate non-matching probe rows)
    dynamic_filters: Tuple[Tuple[str, int, bool], ...] = ()

    @property
    def fields(self):
        if self.mark is not None:
            return self.child.fields + ((self.mark, T.BOOLEAN),)
        return self.child.fields

    @property
    def children(self):
        return (self.child, self.source)


@dataclasses.dataclass(frozen=True)
class ScalarApply(PlanNode):
    """Append an uncorrelated single-row subquery's outputs as broadcast
    columns (reference: EnforceSingleRowNode + cross join of a 1-row side)."""

    child: PlanNode
    subquery: PlanNode

    @property
    def fields(self):
        return self.child.fields + self.subquery.fields

    @property
    def children(self):
        return (self.child, self.subquery)


@dataclasses.dataclass(frozen=True)
class Window(PlanNode):
    """Window functions over one (partition, order) spec (reference
    WindowNode). Output = child fields + one field per function; rows come
    out sorted by (partition, order)."""

    child: PlanNode
    partition_exprs: Tuple[RowExpression, ...]
    order_keys: Tuple[SortKey, ...]
    funcs: Tuple[object, ...]  # ops.window.WindowFunc

    @property
    def fields(self):
        return self.child.fields + tuple(
            (f.name, f.output_type) for f in self.funcs
        )

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Sort(PlanNode):
    child: PlanNode
    keys: Tuple[SortKey, ...]

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class TopN(PlanNode):
    child: PlanNode
    keys: Tuple[SortKey, ...]
    count: int

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Limit(PlanNode):
    child: PlanNode
    count: int

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Distinct(PlanNode):
    child: PlanNode

    @property
    def fields(self):
        return self.child.fields

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Union(PlanNode):
    """UNION ALL of same-arity inputs (reference UnionNode); inputs are
    renamed to the first input's channels by the planner."""

    inputs: Tuple[PlanNode, ...]
    distinct: bool = False

    @property
    def fields(self):
        return self.inputs[0].fields

    @property
    def children(self):
        return self.inputs


@dataclasses.dataclass(frozen=True)
class Output(PlanNode):
    """Final projection to user-visible column names (reference OutputNode)."""

    child: PlanNode
    channels: Tuple[str, ...]
    titles: Tuple[str, ...]

    @property
    def fields(self):
        return tuple(
            (t, self.child.field_type(c))
            for c, t in zip(self.channels, self.titles)
        )

    @property
    def children(self):
        return (self.child,)


def _sort_key_str(k) -> str:
    """`expr desc nulls first` rendering for one SortKey (reference
    planPrinter orderings)."""
    s = f"{k.expr} {'asc' if k.ascending else 'desc'}"
    if k.nulls_first is not None:
        s += " nulls first" if k.nulls_first else " nulls last"
    return s


def plan_tree_str(
    node: PlanNode, indent: int = 0, collector=None, stats_of=None
) -> str:
    """EXPLAIN-style rendering (reference sql/planner/planPrinter). With a
    StatsCollector (exec/stats.py) this is the EXPLAIN ANALYZE view — per-
    operator wall/rows/bytes/retries (reference ExplainAnalyzeContext +
    PlanNodeStatsSummarizer). `stats_of(node)` (plan/stats.PlanStats)
    annotates ESTIMATED rows, the reference's `{rows: N}` cost prints."""
    pad = "  " * indent
    name = type(node).__name__
    detail = ""
    if isinstance(node, TableScan):
        detail = f" {node.table} [{', '.join(c for c, _, _ in node.columns)}]"
        if node.dynamic_filters:
            dfs = ", ".join(
                f"{fid}->{ch}" + ("" if apply else " (hints)")
                for fid, ch, _src, apply in node.dynamic_filters
            )
            detail += f" [df: {dfs}]"
    elif isinstance(node, Filter):
        detail = f" [{node.predicate}]"
        if node.dynamic_filters:
            detail += " [df: " + ", ".join(
                f"{fid}->{ch}" for fid, ch in node.dynamic_filters
            ) + "]"
    elif isinstance(node, Sample):
        detail = f" [bernoulli {node.fraction * 100:g}%]"
    elif isinstance(node, Project):
        detail = f" [{', '.join(f'{n} := {e}' for n, e in zip(node.names, node.exprs))}]"
    elif isinstance(node, Aggregate):
        keys = ", ".join(node.group_names)
        aggs = ", ".join(
            f"{a.name} := {a.func}({a.input}, {a.input2})"
            if a.input2 is not None
            else f"{a.name} := {a.func}({a.input})"
            for a in node.aggs
        )
        detail = f" [keys: {keys}] [{aggs}]"
        if node.mask is not None:
            detail += f" [mask: {node.mask}]"
    elif isinstance(node, Join):
        pairs = ", ".join(
            f"{l} = {r}" for l, r in zip(node.left_keys, node.right_keys)
        )
        detail = f" [{node.kind}] [{pairs}]" + (
            f" [residual: {node.residual}]" if node.residual else ""
        )
        if node.dynamic_filters:
            detail += " [df: " + ", ".join(
                f"{fid}<-key{i}" for fid, i, _c in node.dynamic_filters
            ) + "]"
    elif isinstance(node, SemiJoin):
        pairs = ", ".join(
            f"{l} = {r}" for l, r in zip(node.probe_keys, node.source_keys)
        )
        detail = f" [{'anti' if node.anti else 'semi'}] [{pairs}]"
    elif isinstance(node, (Sort, TopN)):
        keys = ", ".join(_sort_key_str(k) for k in node.keys)
        detail = f" [{keys}]"
        if isinstance(node, TopN):
            detail = f" [{node.count}]{detail}"
    elif isinstance(node, Window):
        parts = ", ".join(str(e) for e in node.partition_exprs)
        order = ", ".join(_sort_key_str(k) for k in node.order_keys)
        funcs = ", ".join(getattr(f, "name", str(f)) for f in node.funcs)
        detail = f" [partition: {parts}] [order: {order}] [{funcs}]"
    elif isinstance(node, Unnest):
        detail = f" [{', '.join(node.elem_channels)}]"
        if node.ordinality_channel is not None:
            detail += f" [ordinality: {node.ordinality_channel}]"
    elif isinstance(node, Union):
        detail = f" [{len(node.inputs)} inputs]" + (
            " [distinct]" if node.distinct else ""
        )
    elif isinstance(node, Limit):
        detail = f" [{node.count}]"
    elif isinstance(node, Output):
        detail = f" [{', '.join(node.titles)}]"
    elif isinstance(node, (Distinct, SingleRow, ScalarApply)):
        # name-only nodes: no config beyond their children. The explicit
        # branch keeps the prestolint exhaustiveness surface green — a
        # NEW node class must show up here deliberately, one way or the
        # other.
        pass
    if name == "Exchange":
        keys = ", ".join(str(k) for k in node.keys)
        detail = f" [{node.kind}]" + (f" [{keys}]" if keys else "")
    if name == "AggFinalize":
        detail = f" [{', '.join(a.name for a in node.aggs)}]"
    stat = ""
    if collector is not None:
        s = collector.lookup(node)
        if s is not None:
            stat = " " + s.line()
    if stats_of is not None:
        try:
            est = stats_of(node)
            stat += f" {{est: {est.rows:,.0f} rows}}"
        except Exception:  # noqa: BLE001 — estimates are best-effort
            # decoration; EXPLAIN itself must never fail on a stats gap
            pass
    lines = [f"{pad}- {name}{detail}{stat}"]
    for c in node.children:
        lines.append(plan_tree_str(c, indent + 1, collector, stats_of))
    return "\n".join(lines)
