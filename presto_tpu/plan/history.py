"""History-based adaptive execution: the observed-cardinality feedback
store (ROADMAP item 3; reference: Presto's history-based optimizer,
presto-main/.../cost/HistoryBasedPlanStatisticsCalculator + the
HistoricalStatisticsEquivalentPlanMarkingOptimizer that keys plans by
canonical form).

The engine already *measures* the truth — EXPLAIN ANALYZE per-node row
counts, hybrid-join partition/spill outcomes, matview refresh walls —
then throws it away at query end. This module closes the loop:

* `fingerprint(node)` — a SEMANTIC key for a plan subtree, not a
  positional one. A join frame digests the UNORDERED set of relational
  atoms beneath it (base relations, applied predicates, barrier
  sub-plans), so `(A ⋈ B) ⋈ C` and `A ⋈ (B ⋈ C)` agree on the final
  frame {A,B,C} while each intermediate keeps its own {A,B} / {B,C}
  key. That invariance is the whole point: the greedy join orderer
  evaluates CANDIDATE subtrees that were never executed in that shape,
  and they must still hit observations recorded from the shape that
  DID run. Literals bound from EXECUTE parameters (`ir.Literal.param`)
  contribute their type only, matching the plan-cache skeleton rule.
* `HistoryStore` — a process-wide, byte-bounded LRU
  (exec/qcache.HISTORY_CACHE, snapshot in /v1/status like the others)
  of per-frame observations: rows, static estimate at record time,
  hybrid-join partition/recursion outcomes, matview refresh walls.
  Entries record the tables they depend on and their connector
  snapshot versions; a `table_version` bump invalidates on the next
  lookup (the uncacheable-never-stale rule: unversioned connectors are
  never recorded). A monotone `generation` bumps on every record /
  invalidation so plan- and estimate-caches keyed on it can never
  serve estimates derived from a superseded history.
* Misprediction decay — when a fresh observation deviates >= 2x from
  the stored one, the entry is counted against; two strikes and it is
  dropped (plus the `adaptive_plan` breaker, which force-reverts the
  whole plane to static plans after repeated faults).

Consumers: plan/stats.StatsDeriver (join ordering, build/probe sides,
broadcast switching), exec/stream hybrid-join sizing, matview delta-vs-
full, and the coordinator's mid-query replan (server/cluster.py), all
behind the single-parse PRESTO_TPU_FEEDBACK knob (server/knobs.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import weakref
from typing import Dict, Optional, Tuple

from ..exec.qcache import HISTORY_CACHE, plan_tables, table_versions
from ..expr import ir
from . import nodes as N

# deviation factor that counts as a misprediction, and how many strikes
# drop the entry (decay): history that keeps disagreeing with reality
# must stop steering plans
MISPREDICT_FACTOR = 2.0
MISPREDICT_LIMIT = 2
# EMA weight of the newest observation when refreshing a live entry
_EMA = 0.5
# nominal per-entry size for the byte bound (a frozen dataclass of
# scalars + small tuples; exact sizeof is not worth a deep walk)
_ENTRY_BYTES = 256


# ---------------------------------------------------------------------------
# semantic plan-subtree fingerprints
# ---------------------------------------------------------------------------


def _expr_atom(e) -> str:
    """Canonical digest text of an expression. Param-tagged literals are
    opaque (type only): one skeleton, one history key, any bound value —
    the same rule that makes plan-cache skeleton reuse sound."""
    if isinstance(e, ir.ColumnRef):
        return f"c:{e.name}"
    if isinstance(e, ir.Literal):
        if e.param is not None:
            return f"p:{e.type}"
        return f"l:{e.value!r}"
    if isinstance(e, ir.Call):
        inner = ",".join(_expr_atom(a) for a in e.args)
        return f"f:{e.name}({inner})"
    if isinstance(e, ir.Lambda):
        return f"lam:{_expr_atom(e.body)}"
    return f"e:{type(e).__name__}"


def _digest(head: str, atoms) -> str:
    h = hashlib.sha1(head.encode())
    for a in sorted(atoms):
        h.update(b"\x00")
        h.update(a.encode())
    return f"{head.split(':', 1)[0]}:{h.hexdigest()[:20]}"


# node classes whose observed output rows are worth recording (everything
# else either preserves its child's count or is trivially bounded)
_RECORDABLE = (
    N.TableScan, N.Filter, N.Join, N.SemiJoin, N.Aggregate, N.Distinct,
    N.Union,
)


def _frame(node, memo: Dict[int, tuple]) -> tuple:
    """(fingerprint|None, atom frozenset, deterministic) for a subtree.

    Atom sets flow upward through row-preserving nodes; barrier nodes
    (aggregates, limits, ...) collapse their subtree into one opaque
    atom so a join above them still has an order-invariant frame. A
    nondeterministic subtree (TABLESAMPLE) poisons every ancestor's
    fingerprint — its observed counts are not reusable."""
    got = memo.get(id(node))
    if got is not None:
        return got
    out = _frame_uncached(node, memo)
    memo[id(node)] = out
    return out


def _frame_uncached(node, memo) -> tuple:
    if isinstance(node, N.TableScan):
        atoms = frozenset({f"rel:{node.catalog}.{node.table}"})
        return _digest("rel", atoms), atoms, True
    if isinstance(node, N.Filter):
        fp, atoms, det = _frame(node.child, memo)
        atoms = atoms | {f"pred:{_expr_atom(node.predicate)}"}
        return (_digest("rel", atoms) if det else None), atoms, det
    if isinstance(node, N.Join):
        lfp, latoms, ldet = _frame(node.left, memo)
        rfp, ratoms, rdet = _frame(node.right, memo)
        det = ldet and rdet
        atoms = latoms | ratoms
        if node.kind != "inner":
            atoms = atoms | {f"outer:{node.kind}"}
        if node.residual is not None:
            atoms = atoms | {f"pred:{_expr_atom(node.residual)}"}
        return (_digest("join", atoms) if det else None), atoms, det
    if isinstance(node, N.SemiJoin):
        cfp, catoms, cdet = _frame(node.child, memo)
        sfp, _satoms, sdet = _frame(node.source, memo)
        det = cdet and sdet
        keys = ",".join(_expr_atom(k) for k in node.probe_keys)
        atoms = catoms | {f"semi:{int(node.anti)}:{node.mark}:{sfp}:{keys}"}
        return (_digest("rel", atoms) if det else None), atoms, det
    if isinstance(node, N.Aggregate):
        cfp, _catoms, det = _frame(node.child, memo)
        groups = sorted(_expr_atom(e) for e in node.group_exprs)
        fp = _digest("agg", [f"src:{cfp}"] + [f"g:{g}" for g in groups])
        return (fp if det else None), frozenset({f"sub:{fp}"}), det
    if isinstance(node, N.Distinct):
        cfp, _catoms, det = _frame(node.child, memo)
        fields = sorted(f for f, _t in node.fields)
        fp = _digest("agg", [f"src:{cfp}", "distinct"]
                     + [f"g:{f}" for f in fields])
        return (fp if det else None), frozenset({f"sub:{fp}"}), det
    if isinstance(node, N.Union):
        subs = [_frame(c, memo) for c in node.children]
        det = all(d for _f, _a, d in subs)
        fp = _digest("union", [f"src:{f}" for f, _a, _d in subs])
        return (fp if det else None), frozenset({f"sub:{fp}"}), det
    if isinstance(node, N.Sample):
        # sampled counts are per-seed noise: never recorded, never reused
        _f, atoms, _d = _frame(node.child, memo)
        return None, atoms | {"sample"}, False
    if isinstance(node, (N.Limit, N.TopN)):
        cfp, _catoms, det = _frame(node.child, memo)
        fp = _digest("limit", [f"src:{cfp}", f"n:{node.count}"])
        return None, frozenset({f"sub:{fp}"}), det
    children = node.children
    if len(children) == 1:
        # row-preserving pass-through (Project/Sort/Window/Output/...):
        # same frame, same fingerprint as the child
        return _frame(children[0], memo)
    if not children:
        return None, frozenset({f"leaf:{type(node).__name__}"}), True
    subs = [_frame(c, memo) for c in children]
    det = all(d for _f, _a, d in subs)
    fp = _digest(f"op:{type(node).__name__}",
                 [f"src:{f}" for f, _a, _d in subs])
    return None, frozenset({f"sub:{fp}"}), det


def fingerprint(node, memo: Optional[Dict[int, tuple]] = None
                ) -> Optional[str]:
    """Semantic history key for one plan subtree (None = not keyable:
    nondeterministic, or a node kind with nothing worth recording).
    Pass a shared `memo` dict when fingerprinting many nodes of one
    tree — the walk is then linear in the tree, not quadratic."""
    return _frame(node, memo if memo is not None else {})[0]


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HistoryEntry:
    """One observed frame. rows is an EMA over observations; est_rows is
    the STATIC estimate at first record time (the error surfaces compare
    the two). hybrid_* / delta_per_row_s / full_wall_s are the execution-
    setup feedback channels (stream.py, matview/manager.py)."""

    rows: Optional[float]
    est_rows: Optional[float]
    n: int
    tables: Tuple[str, ...]
    versions: Tuple[int, ...]
    catalog_ref: object  # weakref.ref
    kind: str = ""
    mispredicts: int = 0
    hybrid_parts: int = 0
    hybrid_depth: int = 0
    delta_per_row_s: Optional[float] = None
    full_wall_s: Optional[float] = None


class FeedbackStats:
    """Counters for the feedback plane (obs/export.py publishes them as
    presto_feedback_*; EXPLAIN ANALYZE's `-- feedback:` footer and
    system.runtime.plan_history render the same snapshot)."""

    __slots__ = (
        "hits", "misses", "records", "invalidations", "decays",
        "mispredictions", "replans", "err_sum", "err_n", "_lock",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.hits = 0
            self.misses = 0
            self.records = 0
            self.invalidations = 0
            self.decays = 0
            self.mispredictions = 0
            self.replans = 0
            self.err_sum = 0.0  # sum of |est-observed| / max(observed, 1)
            self.err_n = 0

    def snapshot(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "records": self.records,
                "invalidations": self.invalidations,
                "decays": self.decays,
                "mispredictions": self.mispredictions,
                "replans": self.replans,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "mean_abs_rel_err": (
                    round(self.err_sum / self.err_n, 4) if self.err_n
                    else None
                ),
            }


class HistoryStore:
    """Record/lookup over exec/qcache.HISTORY_CACHE with the snapshot-
    version validity rule of the plan/result caches, plus the generation
    counter the estimate caches key on."""

    def __init__(self, cache=HISTORY_CACHE):
        self.cache = cache
        self.stats = FeedbackStats()
        self._lock = threading.Lock()
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def _bump(self) -> None:
        with self._lock:
            self._generation += 1

    def reset(self) -> None:
        self.cache.clear()
        self.stats.reset()
        self._bump()

    @staticmethod
    def _key(fp: str, catalog) -> str:
        """Store key: fingerprint scoped by catalog identity. One process
        serves many catalogs (the in-process cluster's worker threads,
        test oracles) and fingerprints only hash table NAMES, so two
        catalogs with a same-named table would otherwise clobber each
        other's observations. The weakref on the entry still guards
        against id() reuse after the owner is collected."""
        return f"{fp}@{id(catalog):x}"

    # -- write side --

    def record(self, fp: Optional[str], *, catalog, tables,
               rows: Optional[float] = None,
               est_rows: Optional[float] = None, kind: str = "",
               hybrid: Optional[Tuple[int, int]] = None,
               delta_per_row_s: Optional[float] = None,
               full_wall_s: Optional[float] = None) -> bool:
        """Fold one observation into the store. Unversioned tables are
        never recorded (their entries could not be invalidated). A rows
        observation that contradicts a live entry >= MISPREDICT_FACTOR
        counts a strike; MISPREDICT_LIMIT strikes decay the entry."""
        if fp is None:
            return False
        tables = tuple(tables)
        versions = table_versions(catalog, tables)
        if versions is None:
            return False
        key = self._key(fp, catalog)
        old = self.cache.get(key, count=False)
        live = (
            old is not None
            and old.catalog_ref() is catalog
            and old.tables == tables
            and old.versions == versions
        )
        with self.stats._lock:
            self.stats.records += 1
            if rows is not None and est_rows is not None:
                self.stats.err_sum += min(
                    abs(est_rows - rows) / max(rows, 1.0), 100.0
                )
                self.stats.err_n += 1
        if live and rows is not None and old.rows is not None:
            hi, lo = max(rows, old.rows, 1.0), max(min(rows, old.rows), 1.0)
            if hi / lo >= MISPREDICT_FACTOR:
                with self.stats._lock:
                    self.stats.mispredictions += 1
                if old.mispredicts + 1 >= MISPREDICT_LIMIT:
                    self.cache.invalidate(key)
                    with self.stats._lock:
                        self.stats.decays += 1
                    self._bump()
                    return True
                old = dataclasses.replace(
                    old, mispredicts=old.mispredicts + 1
                )
        if live:
            new = dataclasses.replace(
                old,
                rows=(
                    old.rows if rows is None else
                    rows if old.rows is None else
                    old.rows * (1 - _EMA) + rows * _EMA
                ),
                est_rows=old.est_rows if est_rows is None else (
                    old.est_rows if old.est_rows is not None else est_rows
                ),
                n=old.n + 1,
                kind=old.kind or kind,
                hybrid_parts=hybrid[0] if hybrid else old.hybrid_parts,
                hybrid_depth=hybrid[1] if hybrid else old.hybrid_depth,
                delta_per_row_s=(
                    delta_per_row_s if delta_per_row_s is not None
                    else old.delta_per_row_s
                ),
                full_wall_s=(
                    full_wall_s if full_wall_s is not None
                    else old.full_wall_s
                ),
            )
        else:
            new = HistoryEntry(
                rows=rows, est_rows=est_rows, n=1, tables=tables,
                versions=versions, catalog_ref=weakref.ref(catalog),
                kind=kind,
                hybrid_parts=hybrid[0] if hybrid else 0,
                hybrid_depth=hybrid[1] if hybrid else 0,
                delta_per_row_s=delta_per_row_s,
                full_wall_s=full_wall_s,
            )
        self.cache.put(key, new, nbytes=_ENTRY_BYTES)
        self._bump()
        return True

    # -- read side --

    def lookup(self, fp: Optional[str], catalog) -> Optional[HistoryEntry]:
        """Validated entry for a fingerprint, or None. Stale entries
        (catalog identity or any table_version moved) are dropped HERE —
        the lookup is the invalidation point, like the plan cache."""
        if fp is None:
            return None
        key = self._key(fp, catalog)
        ent = self.cache.get(key, count=False)
        if ent is None:
            with self.stats._lock:
                self.stats.misses += 1
            return None
        if (
            # owner collected (and its id() reused): unverifiable
            ent.catalog_ref() is not catalog
            or table_versions(catalog, ent.tables) != ent.versions
        ):
            self.cache.invalidate(key)
            with self.stats._lock:
                self.stats.invalidations += 1
                self.stats.misses += 1
            self._bump()
            return None
        with self.stats._lock:
            self.stats.hits += 1
        return ent

    def observed_rows(self, fp: Optional[str], catalog) -> Optional[float]:
        ent = self.lookup(fp, catalog)
        return None if ent is None or ent.rows is None else float(ent.rows)

    def wants_observation(self, root, catalog) -> bool:
        """True when the plan has at least one recordable frame without a
        live entry — drives the observe-once policy: a plan whose frames
        are all remembered never pays the collector-instrumented run."""
        memo: Dict[int, tuple] = {}
        missing = [False]

        def visit(n):
            if missing[0] or not isinstance(n, _RECORDABLE):
                return
            fp = fingerprint(n, memo)
            if fp is None:
                return
            ent = self.cache.get(self._key(fp, catalog), count=False)
            if (
                ent is None
                or ent.rows is None
                or ent.catalog_ref() is not catalog
                or table_versions(catalog, ent.tables) != ent.versions
            ):
                missing[0] = True

        _walk_plan(root, visit)
        return missing[0]

    def record_plan(self, root, collector, catalog) -> int:
        """Fold one executed plan's collector measurements into the store
        (the query-completion hook). Returns entries recorded."""
        collector.resolve()
        memo: Dict[int, tuple] = {}
        deriver = _static_deriver(catalog)
        done = 0

        def visit(n):
            nonlocal done
            if not isinstance(n, _RECORDABLE):
                return
            ns = collector.lookup(n)
            if ns is None or not ns.calls:
                return
            fp = fingerprint(n, memo)
            if fp is None:
                return
            tables = plan_tables(n)
            if not tables:
                return
            try:
                est = float(deriver.stats(n).rows)
            except Exception:  # noqa: BLE001 — estimate is bookkeeping
                est = None
            if self.record(fp, catalog=catalog, tables=tables,
                           rows=float(ns.rows_out), est_rows=est,
                           kind=type(n).__name__):
                done += 1

        _walk_plan(root, visit)
        return done

    def rows_snapshot(self, limit: int = 256):
        """(fingerprint, entry) pairs, most recently used last — the
        system.runtime.plan_history page source."""
        with self.cache._lock:
            items = list(self.cache._data.items())[-limit:]
        return [(k.rsplit("@", 1)[0], v) for k, (v, _nb) in items]


def _walk_plan(node, visit) -> None:
    visit(node)
    for c in node.children:
        _walk_plan(c, visit)


def _static_deriver(catalog):
    from .stats import StatsDeriver

    return StatsDeriver(catalog, use_history=False)


# ---------------------------------------------------------------------------
# gating + process-wide instance
# ---------------------------------------------------------------------------


# module refs resolved on first use, NOT at import (plan/ must stay
# importable without server/exec) and NOT per call — feedback_on sits
# on every plan-cache key build, where import-machinery overhead would
# eat the serving fast path's latency budget
_gate_mods: Optional[tuple] = None


def feedback_on() -> bool:
    """The one gate every consumer checks: the PRESTO_TPU_FEEDBACK knob
    AND the adaptive_plan breaker (fallback = today's static plans)."""
    global _gate_mods
    if _gate_mods is None:
        from ..exec.breaker import BREAKERS
        from ..server import knobs

        _gate_mods = (knobs, BREAKERS)
    knobs, BREAKERS = _gate_mods
    return knobs.feedback_enabled() and BREAKERS.allow("adaptive_plan")


def plan_env_token() -> int:
    """History generation for plan-environment cache keys; a constant
    when the plane is off so toggling the knob also re-plans."""
    return HISTORY.generation if feedback_on() else -1


class AdaptiveReplan(RuntimeError):
    """Raised at an exchange boundary when a stage's observed output
    contradicts its estimate grossly enough that the downstream plan is
    presumed wrong. NOT retryable by the scheduler's same-plan loop —
    the session layer catches it, re-plans against the now-updated
    history, and re-runs (server/cluster.py)."""

    retryable = False
    adaptive = True


HISTORY = HistoryStore()
