"""Maintenance planner: is a view's plan delta-patchable, and how?

The reference engine splits every aggregation into PARTIAL and FINAL
stages whose intermediate states merge associatively
(AggregationNode.Step); incremental view maintenance is the same
algebra applied across TIME instead of across drivers — new rows form a
delta page, the view's core plan runs over just the delta, and the
delta result merges into the stored result with the same merge
functions `ops.aggregate.decompose_partial` already uses. A plan is
delta-patchable when that merge is exact:

  'aggregate' — Filter/Project/TableScan/Union-all feeding one
      Aggregate whose functions all have closed-form merges
      (count/sum → sum, min/max → min/max, checksum → sum/xor). Old
      result + delta result re-aggregate by the same group keys.
  'append' — a pure Filter/Project/TableScan/Union-all pipeline
      (rows in = rows out, per row). Delta rows simply append;
      Sort/TopN/Limit/Distinct terminals stay exact because for pure
      appends top-N(old ∪ delta) ⊆ top-N(old) ∪ delta and
      distinct(old ∪ delta) = distinct(distinct(old) ∪ delta).

Everything else (joins, window, avg/percentile-style non-decomposable
aggregates, non-deterministic plans) is recompute-only: the manager
falls back to full re-execution and records why.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

from .. import types as T
from ..connectors.memory import MemoryCatalog
from ..exec import qcache
from ..exec.executor import Executor
from ..expr.ir import ColumnRef
from ..ops.aggregate import AggSpec
from ..ops.union import concat_pages
from ..page import Page
from ..plan import nodes as N


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


# Largest delta, as a fraction of the base tables' row count, that a
# delta refresh/patch will process before falling back to a full
# recompute — past this point re-execution is cheaper than the
# scan_delta + merge pipeline.
DELTA_MAX_FRAC = _env_float("PRESTO_TPU_MATVIEW_DELTA_MAX_FRAC", 0.2)

# Master toggle for the qcache "patch" verdict (patch.py). 0 restores
# the PR 8 behavior: any base-table write invalidates the cached result.
PATCH_ENABLED = _env_float("PRESTO_TPU_MATVIEW_PATCH", 1) != 0

# Background refresh cadence for MatViewManager.start_auto_refresh();
# 0 disables the thread unless an explicit interval is passed.
REFRESH_INTERVAL_S = _env_float("PRESTO_TPU_MATVIEW_REFRESH_INTERVAL_S", 0.0)


# Aggregation functions whose partial states merge exactly — mirrors
# ops.aggregate.decompose_partial's closed-form cases. avg/stddev merge
# via cmoments pairs and approx_distinct via sketch union in the
# partial/final path, but the STORED view only keeps final values, so
# they are not re-mergeable here.
MERGEABLE_AGGS = ("count", "count_star", "checksum", "sum", "min", "max")

_TERMINALS = (N.Sort, N.TopN, N.Limit, N.Distinct)
_APPEND_OK = (N.TableScan, N.Filter, N.Project, N.Union)


@dataclasses.dataclass(frozen=True)
class MaintenancePlan:
    """How to maintain one view incrementally.

    kind      — 'aggregate' | 'append'
    core      — the plan subtree to re-run over delta pages (the
                Aggregate for 'aggregate', the whole pipeline for
                'append'); channel-named, no Output wrapper.
    channels  — engine channel names of the stored columns (the
                Output.channels of the view plan).
    titles    — user-visible names (Output.titles) — the stored table's
                column names.
    terminals — Sort/TopN/Limit/Distinct nodes peeled off above the
                core, outermost first; re-applied after every merge.
    types     — channel -> Type for the stored columns.
    group_names / merge_aggs — 'aggregate' only: group-by channels and
                the AggSpecs that re-aggregate old+delta rows.
    tables    — base tables the core scans.
    """

    kind: str
    core: N.PlanNode
    channels: Tuple[str, ...]
    titles: Tuple[str, ...]
    terminals: Tuple[N.PlanNode, ...]
    types: Dict[str, T.Type]
    group_names: Tuple[str, ...] = ()
    merge_aggs: Tuple[AggSpec, ...] = ()
    tables: Tuple[str, ...] = ()


def _expr_columns(expr) -> Tuple[str, ...]:
    names = []
    qcache._walk(
        expr,
        lambda o: names.append(o.name) if isinstance(o, ColumnRef) else None,
    )
    return tuple(names)


def _check_append_subtree(node) -> Optional[str]:
    """None when `node` is a pure row-preserving-per-input pipeline
    (each input row maps to at most one output row, independently of
    every other row), else the rejection reason."""
    if isinstance(node, N.Union):
        if node.distinct:
            return "UNION DISTINCT"
    elif not isinstance(node, _APPEND_OK):
        return type(node).__name__
    for child in node.children:
        reason = _check_append_subtree(child)
        if reason is not None:
            return reason
    return None


def classify(plan) -> Tuple[Optional[MaintenancePlan], str]:
    """(MaintenancePlan, "") when `plan` (an optimized N.Output tree) is
    delta-patchable, else (None, reason) — the reason surfaces in
    EXPLAIN ANALYZE and system.runtime.materialized_views."""
    if not isinstance(plan, N.Output):
        return None, "not an Output plan"
    if len(set(plan.titles)) != len(plan.titles):
        return None, "duplicate output column names"
    if not qcache.plan_is_deterministic(plan):
        return None, "non-deterministic plan"
    chans = set(plan.channels)

    # Peel order-shaping terminals; the merge path re-applies them to
    # old∪delta. Their sort keys must survive the Output projection —
    # the stored table only keeps plan.channels.
    terminals = []
    core = plan.child
    while isinstance(core, _TERMINALS):
        if isinstance(core, (N.Sort, N.TopN)):
            for k in core.keys:
                missing = [c for c in _expr_columns(k.expr) if c not in chans]
                if missing:
                    return None, f"sort key over dropped column {missing[0]}"
        if isinstance(core, N.Distinct):
            dropped = [n for n, _t in core.fields if n not in chans]
            if dropped:
                return None, f"DISTINCT over dropped column {dropped[0]}"
        terminals.append(core)
        core = core.child

    try:
        types = {n: t for n, t in core.fields if n in chans}
    except Exception:  # noqa: BLE001 — field_type on odd subtree: opaque
        return None, "untyped core plan"
    missing = [c for c in plan.channels if c not in types]
    if missing:
        return None, f"output channel {missing[0]} not produced by core"
    tables = qcache.plan_tables(plan)
    if not tables:
        return None, "no base tables"

    if isinstance(core, N.Aggregate):
        # TopN/Limit above an aggregation would need retraction when a
        # delta shifts group totals across the cutoff — not append-only.
        for tn in terminals:
            if isinstance(tn, (N.TopN, N.Limit, N.Distinct)):
                return None, "LIMIT/TopN/DISTINCT above an aggregation"
        bad = [a.func for a in core.aggs if a.func not in MERGEABLE_AGGS]
        if bad:
            return None, f"non-decomposable aggregate {bad[0]}"
        if core.mask is not None:
            # fused mask only references core.child columns — fine; the
            # delta run re-applies it. Nothing to check.
            pass
        needed = set(core.group_names) | {a.name for a in core.aggs}
        dropped = needed - chans
        if dropped:
            return None, f"aggregation column {sorted(dropped)[0]} dropped"
        reason = _check_append_subtree(core.child)
        if reason is not None:
            return None, f"non-append input to aggregation: {reason}"
        merge_aggs = tuple(
            AggSpec(
                "sum" if a.func in ("count", "count_star", "checksum")
                else a.func,
                ColumnRef(a.name, a.output_type),
                a.name,
                a.output_type,
            )
            for a in core.aggs
        )
        return MaintenancePlan(
            kind="aggregate",
            core=core,
            channels=plan.channels,
            titles=plan.titles,
            terminals=tuple(terminals),
            types=types,
            group_names=core.group_names,
            merge_aggs=merge_aggs,
            tables=tables,
        ), ""

    reason = _check_append_subtree(core)
    if reason is not None:
        return None, reason
    return MaintenancePlan(
        kind="append",
        core=core,
        channels=plan.channels,
        titles=plan.titles,
        terminals=tuple(terminals),
        types=types,
        tables=tables,
    ), ""


class _DeltaOverlay:
    """Catalog view where the named tables contain ONLY their delta rows.
    The executor's table scan goes through catalog.page(), so swapping
    page() is sufficient; metadata calls fall through to the base."""

    def __init__(self, base, deltas: Dict[str, Page]):
        self._base = base
        self._deltas = deltas

    def page(self, table: str) -> Page:
        return self._deltas[table]

    def exact_row_count(self, table: str) -> int:
        return int(self._deltas[table].count)

    def __getattr__(self, name):
        return getattr(self._base, name)


def run_core(catalog, mplan: MaintenancePlan, deltas: Dict[str, Page]) -> Page:
    """Run the view's core plan over the delta rows only. Returns a
    channel-named page (same shape the merge expects)."""
    plan = N.Output(mplan.core, mplan.channels, mplan.channels)
    return Executor(_DeltaOverlay(catalog, deltas)).run(plan)


def merge_pages(mplan: MaintenancePlan, old: Page, delta: Page) -> Page:
    """Fold a delta result into the stored result. Both pages are
    channel-named; the output is channel-named too."""
    if int(delta.count) == 0 and not mplan.terminals:
        return old
    pages = [p for p in (old, delta) if int(p.count) > 0]
    if not pages:
        return old
    both = pages[0] if len(pages) == 1 else concat_pages(pages)
    if mplan.kind == "append" and not mplan.terminals:
        return both

    # Re-aggregate / re-sort old∪delta with an in-memory plan. The scan
    # columns keep channel names so terminal sort keys resolve.
    scan = N.TableScan(
        "memory",
        "__mv_merge__",
        tuple((c, c, mplan.types[c]) for c in mplan.channels),
    )
    node: N.PlanNode = scan
    if mplan.kind == "aggregate":
        node = N.Aggregate(
            node,
            tuple(ColumnRef(g, mplan.types[g]) for g in mplan.group_names),
            mplan.group_names,
            mplan.merge_aggs,
        )
    for tn in reversed(mplan.terminals):
        node = dataclasses.replace(tn, child=node)
    plan = N.Output(node, mplan.channels, mplan.channels)
    cat = MemoryCatalog({"__mv_merge__": both})
    return Executor(cat).run(plan)


def patch_pages(
    catalog, mplan: MaintenancePlan, old: Page, deltas: Dict[str, Page]
) -> Tuple[Page, int]:
    """old (channel-named) + base-table delta pages -> (merged page,
    delta rows consumed)."""
    delta_rows = sum(int(p.count) for p in deltas.values())
    delta = run_core(catalog, mplan, deltas)
    return merge_pages(mplan, old, delta), delta_rows
