"""Materialized-view registry + refresh engine (reference
execution/CreateMaterializedViewTask.java +
RefreshMaterializedViewTask; re-designed: the stored representation is
a plain connector table written through the session's writable catalog,
and refresh is an atomic replace() swap so readers always see one
consistent snapshot).

Concurrency model: `_lock` guards the registry and all per-view
bookkeeping (reads AND writes); `_refresh_lock` serializes refresh/drop
bodies so two refreshers can't interleave their read-compute-swap
windows. Delta refresh re-validates the base-table version vector after
executing over the delta — a racing writer forces a retry, never a
torn merge.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..exec import qcache
from ..exec.executor import Executor
from ..connectors.spi import DeltaUnavailable
from ..page import Page
from ..plan import nodes as N
from . import maintenance


class MatViewStats:
    """Process-lifetime counters for the matview subsystem; surfaced via
    system.runtime.materialized_views and EXPLAIN ANALYZE footers."""

    __slots__ = (
        "refreshes", "delta_refreshes", "full_refreshes", "rows_patched",
        "errors",
    )

    def __init__(self):
        self.refreshes = 0  # REFRESH statements (manual + interval)
        self.delta_refreshes = 0  # refreshes served from scan_delta
        self.full_refreshes = 0  # full recomputes (incl. fallbacks)
        self.rows_patched = 0  # delta rows folded into stored views
        self.errors = 0  # refresh bodies that raised

    def snapshot(self) -> Dict[str, int]:
        return {k: getattr(self, k) for k in self.__slots__}


@dataclasses.dataclass
class MatView:
    """One registered view. `plan` is the optimized (unfragmented)
    Output tree; `mplan` is None for recompute-only views with `reason`
    saying why. versions/tokens are the base-table snapshot the stored
    table currently reflects; tokens=None disables delta refresh until
    the next full refresh records a clean cursor."""

    name: str
    sql: str
    plan: N.PlanNode
    tables: Tuple[str, ...]
    mplan: Optional[maintenance.MaintenancePlan]
    reason: str
    storage_names: Tuple[str, ...] = ()
    versions: Optional[Tuple[int, ...]] = None
    tokens: Optional[Tuple[Any, ...]] = None
    last_refresh_at: float = 0.0
    last_mode: str = "init"  # init | delta | full
    last_reason: str = ""
    rows_patched: int = 0
    refreshes: int = 0


class MatViewManager:
    def __init__(self, session):
        self._session = session
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        self.views: Dict[str, MatView] = {}
        self.stats = MatViewStats()
        self._auto_thread: Optional[threading.Thread] = None
        self._auto_stop = threading.Event()

    # -- planning --

    def _plan(self, sql: str):
        """(plan, tables) for the view query — planned WITHOUT mesh
        fragmenting so classify() sees the logical tree; refresh runs
        the plan on a local executor."""
        from ..sql import tree as t
        from ..sql.parser import parse
        from ..sql.planner import Planner
        from ..plan.optimizer import optimize

        ast = parse(sql)
        if not isinstance(ast, t.Query):
            raise ValueError(
                "CREATE MATERIALIZED VIEW requires a SELECT query"
            )
        s = self._session
        planner = Planner(s.catalog, views=s.views)
        rp = planner.plan_query(ast, outer=None, ctes={})
        channels = tuple(f.channel for f in rp.scope.fields)
        titles = tuple(f.name for f in rp.scope.fields)
        plan = optimize(N.Output(rp.node, channels, titles))
        return plan, qcache.plan_tables(plan)

    def _run_consistent(self, plan):
        """Execute `plan` and return (page, versions, tokens) where the
        page is consistent with the recorded snapshot. Retries when a
        writer races the execution; after 3 tries keeps the page but
        nulls the tokens (delta refresh disabled until a quiet full
        refresh re-records a cursor)."""
        s = self._session
        tables = qcache.plan_tables(plan)
        versions = tokens = None
        page = None
        for _attempt in range(3):
            versions = qcache.table_versions(s.catalog, tables)
            tokens = qcache.delta_tokens(s.catalog, tables)
            page = Executor(s.catalog).run(plan)
            if versions is None:
                return page, None, None
            if qcache.table_versions(s.catalog, tables) == versions:
                return page, versions, tokens
        return page, qcache.table_versions(s.catalog, tables), None

    # -- DDL entry points (session.py dispatch) --

    def create(self, name: str, sql: str, if_not_exists: bool = False):
        s = self._session
        name = name.lower()
        with self._refresh_lock:
            with self._lock:
                exists = name in self.views
            if exists:
                if if_not_exists:
                    return
                raise ValueError(
                    f"materialized view {name!r} already exists"
                )
            if name in s.views:
                raise ValueError(f"view {name!r} already exists")
            if name in s.catalog.table_names():
                raise ValueError(f"table {name!r} already exists")
            plan, tables = self._plan(sql)
            mplan, reason = maintenance.classify(plan)
            storage = tuple(tl.lower() for tl in plan.titles)
            if len(set(storage)) != len(storage):
                raise ValueError(
                    "CREATE MATERIALIZED VIEW requires unique column names"
                )
            page, versions, tokens = self._run_consistent(plan)
            cat = s._writable()
            cat.create_table_from_page(
                name, Page(page.blocks, storage, page.count)
            )
            mv = MatView(
                name=name, sql=sql, plan=plan, tables=tables,
                mplan=mplan, reason=reason, storage_names=storage,
                versions=versions, tokens=tokens,
                last_refresh_at=time.time(), last_mode="full",
                last_reason="initial build", refreshes=1,
            )
            with self._lock:
                self.views[name] = mv
                self.stats.refreshes += 1
                self.stats.full_refreshes += 1

    def drop(self, name: str, if_exists: bool = False):
        name = name.lower()
        with self._refresh_lock:
            with self._lock:
                mv = self.views.pop(name, None)
            if mv is None:
                if if_exists:
                    return
                raise ValueError(
                    f"materialized view {name!r} does not exist"
                )
            cat = self._session._writable()
            if name in cat.table_names():
                cat.drop_table(name)

    def refresh(self, name: str, full: bool = False) -> str:
        """Refresh one view; returns the mode used ('delta' | 'full').
        `full=True` forces a recompute (REFRESH ... FULL)."""
        name = name.lower()
        with self._refresh_lock:
            with self._lock:
                mv = self.views.get(name)
            if mv is None:
                raise ValueError(
                    f"materialized view {name!r} does not exist"
                )
            try:
                return self._refresh_inner(mv, full)
            except Exception:
                with self._lock:
                    self.stats.errors += 1
                raise

    def refresh_all(self) -> None:
        with self._lock:
            names = list(self.views)
        for name in names:
            try:
                self.refresh(name)
            except Exception:  # noqa: BLE001 — auto tick must survive
                pass  # counted in stats.errors by refresh()

    # -- refresh internals (caller holds _refresh_lock) --

    def _refresh_inner(self, mv: MatView, full: bool) -> str:
        if not full and mv.mplan is not None and mv.tokens is not None \
                and mv.versions is not None:
            try:
                mode = self._refresh_delta(mv)
            except DeltaUnavailable as e:
                mode = None
                fallback = f"delta unavailable: {e}"
            else:
                fallback = "delta not applicable (rewrite/large delta/race)"
            if mode is not None:
                return mode
        else:
            fallback = (
                "forced full" if full
                else (mv.reason if mv.mplan is None else "no delta cursor")
            )
        self._refresh_full(mv, fallback)
        return "full"

    def _refresh_delta(self, mv: MatView) -> Optional[str]:
        """Delta refresh; returns 'delta' on success, None when the
        caller should fall back to full (racing writers exhausted the
        retry budget or the delta is too large). Raises DeltaUnavailable
        when compaction swallowed the cursor."""
        s = self._session
        cat = s.catalog
        scan_delta = getattr(cat, "scan_delta", None)
        if scan_delta is None:
            return None
        for _attempt in range(3):
            versions = qcache.table_versions(cat, mv.tables)
            new_tokens = qcache.delta_tokens(cat, mv.tables)
            if versions is None or new_tokens is None:
                return None
            for old_tok, new_tok in zip(mv.tokens, new_tokens):
                # rewrites (upsert/replace/delete) can't be expressed
                # as an append delta
                if new_tok[2] != old_tok[2] or new_tok[0] < old_tok[0]:
                    return None
            if versions == mv.versions:
                # nothing changed — bookkeeping only
                with self._lock:
                    mv.tokens = new_tokens
                    mv.last_refresh_at = time.time()
                    mv.last_mode = "delta"
                    mv.last_reason = "no-op (base unchanged)"
                    mv.refreshes += 1
                    self.stats.refreshes += 1
                    self.stats.delta_refreshes += 1
                return "delta"
            deltas = {}
            total = 0
            base_rows = 0
            for tb, old_tok, new_tok in zip(
                mv.tables, mv.tokens, new_tokens
            ):
                deltas[tb] = scan_delta(tb, old_tok[0], new_tok[0])
                total += int(deltas[tb].count)
                try:
                    base_rows += int(cat.row_count(tb))
                except Exception:  # noqa: BLE001 — stats miss: skip cap
                    pass
            if not self._delta_worthwhile(mv, total, base_rows):
                return None
            wcat = s._writable()
            t0 = time.perf_counter()
            delta = maintenance.run_core(cat, mv.mplan, deltas)
            if qcache.table_versions(cat, mv.tables) != versions:
                continue  # writer raced the delta execution — retry
            if mv.mplan.kind == "append" and not mv.mplan.terminals:
                # stored table stays append-only, so result-cache
                # entries scanning the MV itself remain patchable too
                if int(delta.count):
                    wcat.append(
                        mv.name,
                        Page.from_blocks(
                            list(delta.blocks), list(mv.storage_names),
                            count=delta.count,
                        ),
                    )
            else:
                # the stored table is only written under _refresh_lock
                # (held here), so this read is a consistent snapshot
                old_stored = cat.page(mv.name)
                old = Page.from_blocks(
                    list(old_stored.blocks),
                    list(mv.plan.channels),
                    count=old_stored.count,
                )
                merged = maintenance.merge_pages(mv.mplan, old, delta)
                wcat.replace(
                    mv.name,
                    Page.from_blocks(
                        list(merged.blocks), list(mv.storage_names),
                        count=merged.count,
                    ),
                )
            self._record_refresh_wall(
                mv, delta_per_row_s=(
                    (time.perf_counter() - t0) / max(total, 1)
                ),
            )
            with self._lock:
                mv.versions = versions
                mv.tokens = new_tokens
                mv.last_refresh_at = time.time()
                mv.last_mode = "delta"
                mv.last_reason = f"{total} delta rows"
                mv.rows_patched += total
                mv.refreshes += 1
                self.stats.refreshes += 1
                self.stats.delta_refreshes += 1
                self.stats.rows_patched += total
            return "delta"
        return None

    def _delta_worthwhile(self, mv: MatView, total: int,
                          base_rows: int) -> bool:
        """Delta-vs-full break-even. With history feedback on
        (plan/history.py) and BOTH refresh modes measured for this view,
        the decision is the measured one — predicted delta wall vs the
        last full-recompute wall — instead of the fixed
        PRESTO_TPU_MATVIEW_DELTA_MAX_FRAC row-ratio cap (which stays the
        static fallback and the manual override when feedback is off)."""
        try:
            from ..plan.history import HISTORY, feedback_on

            if feedback_on():
                ent = HISTORY.lookup(
                    f"mv:{mv.name}", self._session.catalog
                )
                if (
                    ent is not None
                    and ent.delta_per_row_s is not None
                    and ent.full_wall_s is not None
                ):
                    return ent.delta_per_row_s * total < ent.full_wall_s
        except Exception as exc:  # noqa: BLE001 — degrade to the cap
            from ..exec.breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))
        return not (
            base_rows and total > maintenance.DELTA_MAX_FRAC * base_rows
        )

    def _record_refresh_wall(self, mv: MatView,
                             delta_per_row_s=None,
                             full_wall_s=None) -> None:
        """Feed observed refresh walls back into the history store. Keyed
        per view with NO table-version dependency: walls measure the
        refresh pipeline, not a data snapshot, and base-table writes are
        exactly when the next refresh needs them."""
        try:
            from ..plan.history import HISTORY, feedback_on

            if feedback_on():
                HISTORY.record(
                    f"mv:{mv.name}", catalog=self._session.catalog,
                    tables=(), kind="MatView",
                    delta_per_row_s=delta_per_row_s,
                    full_wall_s=full_wall_s,
                )
        except Exception as exc:  # noqa: BLE001 — bookkeeping only
            from ..exec.breaker import BREAKERS

            BREAKERS.record_failure("adaptive_plan", repr(exc))

    def _refresh_full(self, mv: MatView, reason: str) -> None:
        s = self._session
        t0 = time.perf_counter()
        page, versions, tokens = self._run_consistent(mv.plan)
        self._record_refresh_wall(
            mv, full_wall_s=time.perf_counter() - t0
        )
        wcat = s._writable()
        wcat.replace(
            mv.name,
            Page.from_blocks(
                list(page.blocks), list(mv.storage_names), count=page.count
            ),
        )
        with self._lock:
            mv.versions = versions
            mv.tokens = tokens
            mv.last_refresh_at = time.time()
            mv.last_mode = "full"
            mv.last_reason = reason
            mv.refreshes += 1
            self.stats.refreshes += 1
            self.stats.full_refreshes += 1

    # -- interval-driven refresh --

    def start_auto_refresh(self, interval_s: Optional[float] = None) -> bool:
        """Spawn the background refresh thread; returns False when the
        effective interval is 0 (disabled) or a thread already runs."""
        iv = (
            maintenance.REFRESH_INTERVAL_S
            if interval_s is None else float(interval_s)
        )
        if iv <= 0:
            return False
        with self._lock:
            if self._auto_thread is not None and self._auto_thread.is_alive():
                return False
            self._auto_stop.clear()
            th = threading.Thread(
                target=self._auto_loop, args=(iv,),
                name="matview-refresh", daemon=True,
            )
            self._auto_thread = th
        th.start()
        return True

    def stop_auto_refresh(self) -> None:
        with self._lock:
            th = self._auto_thread
            self._auto_thread = None
        self._auto_stop.set()
        if th is not None:
            th.join(timeout=5.0)

    def _auto_loop(self, interval_s: float) -> None:
        while not self._auto_stop.wait(interval_s):
            self.refresh_all()

    # -- observability --

    def _staleness(self, mv: MatView) -> int:
        """Versions the view lags its base tables by (0 = fresh)."""
        cat = self._session.catalog
        toks = qcache.delta_tokens(cat, mv.tables)
        if toks is not None and mv.tokens is not None:
            return sum(
                max(int(n[1]) - int(o[1]), 0)
                for o, n in zip(mv.tokens, toks)
            )
        cur = qcache.table_versions(cat, mv.tables)
        if cur is None or mv.versions is None:
            return 0
        return sum(1 for a, b in zip(mv.versions, cur) if a != b)

    def rows(self):
        """system.runtime.materialized_views rows — one dict per view."""
        with self._lock:
            views = list(self.views.values())
        out = []
        for mv in views:
            out.append({
                "name": mv.name,
                "base_tables": ",".join(mv.tables),
                "incremental": mv.mplan is not None,
                "reason": mv.reason,
                "staleness_versions": self._staleness(mv),
                "last_refresh_at": mv.last_refresh_at,
                "last_mode": mv.last_mode,
                "last_reason": mv.last_reason,
                "rows_patched": mv.rows_patched,
                "refreshes": mv.refreshes,
            })
        return out

    def format_summary(self) -> str:
        """One-line `-- matview:` EXPLAIN ANALYZE footer body."""
        with self._lock:
            views = list(self.views.values())
        parts = []
        for mv in views:
            kind = (
                mv.mplan.kind if mv.mplan is not None
                else f"full({mv.reason})"
            )
            parts.append(
                f"{mv.name} {kind} mode={mv.last_mode} "
                f"staleness={self._staleness(mv)} "
                f"patched={mv.rows_patched:,}"
            )
        return "; ".join(parts)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"stats": self.stats.snapshot(), "views": len(self.views)}
