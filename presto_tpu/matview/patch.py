"""qcache's third verdict: PATCH a stale result entry in place.

A result-cache entry whose plan classifies as delta-patchable doesn't
need eviction when its base tables advance — the delta rows since the
entry's recorded tokens run through the view's core plan and merge into
the cached page. Consistency rule (shared with ResultCache.preversions):
read the version vector FIRST, the delta tokens SECOND, then the data —
so a racing writer can only make the patched entry FRESHER than the
versions it claims, never staler; the next lookup re-validates against
current versions and patches again or invalidates.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..connectors.spi import DeltaUnavailable
from ..exec import qcache
from ..exec.stats import page_device_bytes
from ..page import Page
from ..plan import nodes as N
from . import maintenance


def patch_entry(plan, ent, catalog) -> Optional[object]:
    """Return a fresh ResultEntry built by patching `ent` with the
    deltas between ent.tokens and the current snapshot, or None when
    patching is impossible/unprofitable (caller invalidates)."""
    if not maintenance.PATCH_ENABLED:
        return None
    if ent.tokens is None or not isinstance(plan, N.Output):
        return None
    mplan, _reason = maintenance.classify(plan)
    if mplan is None or mplan.tables != ent.tables:
        return None

    scan_delta = getattr(catalog, "scan_delta", None)
    if scan_delta is None:
        return None
    versions = qcache.table_versions(catalog, ent.tables)
    if versions is None:
        return None
    new_tokens = qcache.delta_tokens(catalog, ent.tables)
    if new_tokens is None:
        return None

    deltas = {}
    total_delta = 0
    base_rows = 0
    for tb, old_tok, new_tok in zip(ent.tables, ent.tokens, new_tokens):
        # token = (high_seq, data_version, nonappend_version). A
        # nonappend bump means rows were rewritten/removed — deltas
        # can't express that. A receding high_seq means the table was
        # dropped and recreated.
        if new_tok[2] != old_tok[2] or new_tok[0] < old_tok[0]:
            return None
        try:
            delta = scan_delta(tb, old_tok[0], new_tok[0])
        except DeltaUnavailable:
            return None
        except Exception:  # noqa: BLE001 — connector raced a drop: bail
            return None
        deltas[tb] = delta
        total_delta += int(delta.count)
        try:
            base_rows += int(catalog.row_count(tb))
        except Exception:  # noqa: BLE001 — stats miss: skip the cap
            pass
    if base_rows and total_delta > maintenance.DELTA_MAX_FRAC * base_rows:
        return None

    # Cached pages are title-named (Output renamed them); the merge
    # pipeline runs on channel names. Rename is positional both ways —
    # exactly what Executor._exec_output did.
    old = Page.from_blocks(
        list(ent.page.blocks), list(plan.channels), count=ent.page.count
    )
    merged, _rows = maintenance.patch_pages(catalog, mplan, old, deltas)
    new_page = Page.from_blocks(
        list(merged.blocks), list(plan.titles), count=merged.count
    )
    return dataclasses.replace(
        ent,
        page=new_page,
        versions=versions,
        tokens=new_tokens,
        nbytes=page_device_bytes(new_page),
    )
