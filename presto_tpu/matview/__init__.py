"""Incrementally-maintained materialized views (reference raptor's
materialized-view shadowing + Presto's REFRESH MATERIALIZED VIEW).

Three layers:

  maintenance.py — classifies a view plan as delta-patchable vs
      recompute-only, and executes the delta/merge pipeline over
      connector `scan_delta()` snapshots.
  patch.py — the qcache "patch" verdict: updates a stale result-cache
      entry in place from base-table deltas instead of evicting it.
  manager.py — the session-facing registry: CREATE/REFRESH/DROP
      MATERIALIZED VIEW, interval-driven auto refresh, and the
      system.runtime.materialized_views rows.
"""

from .maintenance import MaintenancePlan, classify  # noqa: F401
from .manager import MatViewManager, MatViewStats  # noqa: F401
from .patch import patch_entry  # noqa: F401
