"""Process-wide metrics plane: counters/gauges/histograms, no deps.

Re-designed equivalent of the reference's JMX/StatLib surface
(airlift stats — CounterStat/DistributionStat exported through
MBeanExporter and scraped by the jmx connector): one process-global
registry the existing silos (qcache, breakers, exchange/wire stats,
scheduler, kernel cache) export into, rendered in Prometheus text
exposition format 0.0.4 at `/v1/metrics` on both server roles and
queryable as `system.runtime.metrics`.

Two export styles, matching how the silos already work:

* **push**: hot paths fold deltas with `counter()` / `observe()`
  (exchange folds at task end, query completions, kernel profile);
* **pull**: process-global snapshot owners (qcache, BREAKERS, the
  kernel profile) register a *producer* callback evaluated at scrape
  time, so serving paths never pay for gauge upkeep.

Histograms use fixed log2 buckets (0.25ms .. ~2min) so two processes'
scrapes aggregate without bucket negotiation.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("presto_tpu.obs")

# (name, type, labels, value) — the unit every surface consumes: the
# Prometheus renderer, system.runtime.metrics, and producer callbacks.
Sample = Tuple[str, str, Tuple[Tuple[str, str], ...], float]

# log2 ladder: 0.25ms doubling to ~2 minutes (20 bounds + +Inf)
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    0.00025 * (2.0 ** i) for i in range(20)
)


def _labels_key(labels: Optional[Dict[str, str]]):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    f = float(value)
    if f.is_integer() and abs(f) < 2 ** 53:
        return str(int(f))
    return repr(f)


class _Histogram:
    __slots__ = ("counts", "total", "count")

    def __init__(self):
        self.counts = [0] * len(BUCKET_BOUNDS)
        self.total = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        # per-bucket counts: one bucket per observation; collect() does
        # the cumulative accumulation the exposition format requires
        for i, bound in enumerate(BUCKET_BOUNDS):
            if seconds <= bound:
                self.counts[i] += 1
                break


class MetricsRegistry:
    """All mutation and iteration under one registry lock; producer
    callbacks run OUTSIDE the lock at scrape time (a producer may take
    its silo's own lock — qcache, breakers — and must never be able to
    deadlock against a concurrent exporter holding ours)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._help: Dict[str, str] = {}
        self._counters: Dict[str, Dict[tuple, float]] = {}
        self._gauges: Dict[str, Dict[tuple, float]] = {}
        self._hists: Dict[str, _Histogram] = {}
        self._producers: Dict[str, Callable[[], List[Sample]]] = {}
        self._scrape_errors = 0

    # -- push API --

    def counter(self, name: str, value: float = 1.0,
                labels: Optional[Dict[str, str]] = None,
                help: str = "") -> None:
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + value

    def declare_counter(self, name: str, help: str = "",
                        labels: Optional[Dict[str, str]] = None) -> None:
        """Ensure the series exists (at 0) so scrapes have a stable
        schema before the first increment."""
        self.counter(name, 0.0, labels, help)

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None,
              help: str = "") -> None:
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(self, name: str, seconds: float, help: str = "") -> None:
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = _Histogram()
            hist.observe(seconds)

    # -- pull API --

    def register_producer(
        self, key: str, fn: Callable[[], List[Sample]]
    ) -> None:
        with self._lock:
            self._producers[key] = fn

    def unregister_producer(self, key: str) -> None:
        with self._lock:
            self._producers.pop(key, None)

    # -- scrape --

    def _run_producers(self) -> List[Sample]:
        with self._lock:
            producers = list(self._producers.items())
        out: List[Sample] = []
        for key, fn in producers:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — scrape must not fail
                log.warning("metrics producer %r failed", key, exc_info=True)
                with self._lock:
                    self._scrape_errors += 1
        return out

    def collect(self) -> List[Sample]:
        """Every sample, push + pull, as flat rows (system.runtime.metrics
        and the Prometheus renderer share this)."""
        from .export import ensure_default_exports

        ensure_default_exports()
        produced = self._run_producers()
        out: List[Sample] = []
        with self._lock:
            for name, series in self._counters.items():
                for key, value in series.items():
                    out.append((name, "counter", key, value))
            for name, series in self._gauges.items():
                for key, value in series.items():
                    out.append((name, "gauge", key, value))
            for name, hist in self._hists.items():
                acc = 0
                for bound, n in zip(BUCKET_BOUNDS, hist.counts):
                    acc += n
                    out.append((
                        name + "_bucket", "histogram",
                        (("le", _fmt_value(bound)),), float(acc),
                    ))
                out.append((
                    name + "_bucket", "histogram", (("le", "+Inf"),),
                    float(hist.count),
                ))
                out.append((name + "_sum", "histogram", (), hist.total))
                out.append((
                    name + "_count", "histogram", (), float(hist.count)
                ))
            out.append((
                "presto_scrape_errors_total", "counter", (),
                float(self._scrape_errors),
            ))
        out.extend(produced)
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        samples = self.collect()
        with self._lock:
            helps = dict(self._help)
        # group samples under their family (histogram suffixes share one
        # TYPE header) preserving first-seen family order
        families: Dict[str, Tuple[str, List[Sample]]] = {}
        order: List[str] = []
        for name, typ, labels, value in samples:
            family = name
            if typ == "histogram":
                for suffix in ("_bucket", "_sum", "_count"):
                    if name.endswith(suffix):
                        family = name[: -len(suffix)]
                        break
            if family not in families:
                families[family] = (typ, [])
                order.append(family)
            families[family][1].append((name, typ, labels, value))
        lines: List[str] = []
        for family in order:
            typ, rows = families[family]
            help_txt = helps.get(family, "")
            if help_txt:
                lines.append(f"# HELP {family} {_escape(help_txt)}")
            lines.append(f"# TYPE {family} {typ}")
            for name, _typ, labels, value in rows:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(value)}"
                )
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test hook: drop every series and producer."""
        with self._lock:
            self._help.clear()
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._producers.clear()
            self._scrape_errors = 0
        from . import export

        export.reset_defaults()


# process-global: one metrics plane per interpreter, shared by the
# coordinator and any in-process workers (separate processes in a real
# deployment each expose their own /v1/metrics)
METRICS = MetricsRegistry()
