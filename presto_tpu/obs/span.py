"""Span trees: one query end-to-end across the fleet.

Re-designed equivalent of the reference's query-wide stats tree
(QueryStats → StageStats → TaskStats → OperatorStats assembled by the
coordinator from task status updates) expressed as a trace: a query
gets a `trace_id`; the coordinator opens phase spans (plan / execute),
per-stage and per-dispatch spans; the trace context (trace_id + parent
span_id) rides the HTTP task spec; workers record their own task spans
against that parent and return them in the task-status payload; the
coordinator merges the fleet's spans into ONE tree.

Retry semantics: every dispatch attempt gets its OWN span under the
same parent — a retried task appears as sibling spans (the failed
attempt with status="error", the retry with status="ok"), never an
overwrite. Merging is idempotent by span_id, last write wins, so a
status polled mid-flight (end=None) is upgraded by the final poll.

Timebase is time.time() so coordinator and worker spans align on the
wall clock; durations of remote spans are computed remotely, so clock
skew shifts placement, not length.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple


def _new_id(n: int = 16) -> str:
    return uuid.uuid4().hex[:n]


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "status", "attrs",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start: float):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.status = "ok"
        self.attrs: Dict[str, object] = {}

    @property
    def wall_s(self) -> float:
        if self.end is None:
            return 0.0
        return max(0.0, self.end - self.start)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


class Trace:
    """One query's span tree. All span mutation happens through the
    trace's lock (begin/finish/add_remote), so status-poll merges from
    puller threads and the coordinator's own phase spans never race."""

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or _new_id()
        self._lock = threading.Lock()
        self._spans: "OrderedDict[str, Span]" = OrderedDict()

    # -- recording --

    def begin(self, name: str, parent: Optional[Span] = None,
              parent_id: Optional[str] = None, **attrs) -> Span:
        span = Span(
            name, self.trace_id, _new_id(12),
            parent.span_id if parent is not None else parent_id,
            time.time(),
        )
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._spans[span.span_id] = span
        return span

    def finish(self, span: Span, status: str = "ok", **attrs) -> Span:
        with self._lock:
            if span.end is None:
                span.end = time.time()
            span.status = status
            if attrs:
                span.attrs.update(attrs)
        return span

    def add_synthetic(self, name: str, parent: Optional[Span],
                      wall_s: float, status: str = "ok", **attrs) -> Span:
        """A span with a known duration but no live start/stop — used to
        graft per-node EXPLAIN ANALYZE stats into the same tree shape
        the cluster path ships."""
        now = time.time()
        span = Span(
            name, self.trace_id, _new_id(12),
            parent.span_id if parent is not None else None,
            now - max(0.0, wall_s),
        )
        span.end = now
        span.status = status
        span.attrs.update(attrs)
        with self._lock:
            self._spans[span.span_id] = span
        return span

    def add_remote(self, span_dicts: Iterable[dict]) -> int:
        """Merge spans shipped from a worker (task-status payload).
        Idempotent by span_id — re-polling a task upgrades the entry in
        place instead of duplicating it. Returns spans merged."""
        n = 0
        with self._lock:
            for d in span_dicts or ():
                try:
                    sid = d["span_id"]
                    span = Span(
                        str(d.get("name", "?")), self.trace_id, sid,
                        d.get("parent_id"), float(d.get("start", 0.0)),
                    )
                    end = d.get("end")
                    span.end = float(end) if end is not None else None
                    span.status = str(d.get("status", "ok"))
                    span.attrs = dict(d.get("attrs") or {})
                except (KeyError, TypeError, ValueError):
                    continue
                self._spans[sid] = span
                n += 1
        return n

    # -- reading --

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans.values())

    def to_dicts(self) -> List[dict]:
        return [s.to_dict() for s in self.spans()]

    def root(self) -> Optional[Span]:
        for s in self.spans():
            if s.parent_id is None:
                return s
        return None

    def children(self, span_id: Optional[str]) -> List[Span]:
        return [s for s in self.spans() if s.parent_id == span_id]

    def orphans(self) -> List[Span]:
        """Spans whose parent never arrived — a merge bug or a lost
        status payload; the fault-tolerance test asserts none."""
        with self._lock:
            ids = set(self._spans)
            return [
                s for s in self._spans.values()
                if s.parent_id is not None and s.parent_id not in ids
            ]

    def exclusive_walls(self) -> List[Tuple[Span, float]]:
        """(span, wall minus children's wall) — the time a span spent
        NOT delegated further down the tree, the critical-path unit."""
        spans = self.spans()
        child_sum: Dict[str, float] = {}
        for s in spans:
            if s.parent_id is not None:
                child_sum[s.parent_id] = (
                    child_sum.get(s.parent_id, 0.0) + s.wall_s
                )
        return [
            (s, max(0.0, s.wall_s - child_sum.get(s.span_id, 0.0)))
            for s in spans
        ]

    def critical_path(self, topk: int = 5) -> List[Tuple[Span, float]]:
        ranked = sorted(
            self.exclusive_walls(), key=lambda p: p[1], reverse=True
        )
        return ranked[:max(1, topk)]


def render_critical_path(trace: Trace, topk: int = 5) -> str:
    """The `-- trace:` EXPLAIN ANALYZE footer — ONE renderer for the
    single-process and cluster paths (acceptance: one source of truth)."""
    root = trace.root()
    total = root.wall_s if root is not None else 0.0
    parts = []
    for span, excl in trace.critical_path(topk):
        pct = f" ({excl / total * 100:.0f}%)" if total > 0 else ""
        flag = "!" if span.status != "ok" else ""
        parts.append(f"{flag}{span.name} {excl * 1e3:.1f}ms{pct}")
    head = f"trace {trace.trace_id} wall {total * 1e3:.1f}ms"
    if not parts:
        return head
    return head + "; top exclusive: " + ", ".join(parts)


class TraceStore:
    """Bounded keep-last-N registry of traces for system.runtime.tasks
    and coordinator-side merging. Workers do NOT register their
    per-task traces here — theirs travel in the status payload so the
    merge path is the same in-process and across real processes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()

    def _keep(self) -> int:
        from ..server import knobs

        return knobs.trace_keep()

    def new_trace(self) -> Trace:
        trace = Trace()
        keep = self._keep()
        with self._lock:
            self._traces[trace.trace_id] = trace
            while len(self._traces) > max(1, keep):
                self._traces.popitem(last=False)
        return trace

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._traces.get(trace_id)

    def recent(self) -> List[Trace]:
        with self._lock:
            return list(self._traces.values())

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()


def enabled() -> bool:
    from ..server import knobs

    return knobs.trace_enabled()


# process-global: the coordinator's (or single-process session's) view
TRACES = TraceStore()
