"""Kernel compile-vs-execute split for KERNEL_CACHE entries.

jax.jit compiles lazily at the FIRST call of the jitted callable, so a
cache entry's first invocation pays trace + lower + compile (+ one
execution) and every later invocation pays dispatch only. Wrapping the
callable at cache-fill time splits those two costs: EXPLAIN ANALYZE
can separate warm-up from steady state, and the metrics plane exports
`presto_kernel_{compile,execute}_*` series.

The wrapper must be exception-transparent: `_kernel_guarded`'s breaker
protocol classifies kernel faults by the exception that escapes the
call — swallowing or re-wrapping it here would break fallback retry.
Only successful calls are recorded.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class KernelProfile:
    """Process-wide compile/execute accounting (the kernel cache itself
    is process-wide, keyed by backend — see exec/qcache.KERNEL_CACHE)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_s = 0.0
        self.executions = 0
        self.execute_s = 0.0

    def record(self, first_call: bool, seconds: float) -> None:
        with self._lock:
            if first_call:
                self.compiles += 1
                self.compile_s += seconds
            else:
                self.executions += 1
                self.execute_s += seconds

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_s": self.compile_s,
                "executions": self.executions,
                "execute_s": self.execute_s,
            }

    def reset(self) -> None:
        with self._lock:
            self.compiles = 0
            self.compile_s = 0.0
            self.executions = 0
            self.execute_s = 0.0

    def wrap(self, fn: Callable) -> "_ProfiledKernel":
        return _ProfiledKernel(self, fn)


class _ProfiledKernel:
    """Callable shim stored in KERNEL_CACHE in place of the raw (jitted)
    function. First successful call = compile bucket (includes the one
    execution jit performs while compiling); later calls = execute
    bucket (dispatch wall — jax dispatch is async, so this is time to
    enqueue, not device time)."""

    __slots__ = ("_profile", "fn", "_compiled", "_lock")

    def __init__(self, profile: KernelProfile, fn: Callable):
        self._profile = profile
        self.fn = fn
        self._compiled = False
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        # the first-call decision must be atomic: two threads racing the
        # first call would otherwise both book the compile bucket
        with self._lock:
            first = not self._compiled
            self._compiled = True
        self._profile.record(first, dt)
        return out


def profiling_enabled() -> bool:
    from ..server import knobs

    return knobs.trace_enabled()


KERNEL_PROFILE = KernelProfile()
