"""Observability plane: span trees + metrics registry + kernel profile.

One subsystem unifying the engine's stats silos (see
docs/observability.md): `Trace`/`TRACES` for per-query span trees that
survive retry and merge across the fleet, `METRICS` for the
process-wide Prometheus-rendered registry, `KERNEL_PROFILE` for the
compile-vs-execute split of KERNEL_CACHE entries.
"""

from .kernelprof import KERNEL_PROFILE, KernelProfile
from .metrics import METRICS, MetricsRegistry
from .span import (
    TRACES,
    Span,
    Trace,
    TraceStore,
    render_critical_path,
)

__all__ = [
    "KERNEL_PROFILE",
    "KernelProfile",
    "METRICS",
    "MetricsRegistry",
    "TRACES",
    "Span",
    "Trace",
    "TraceStore",
    "render_critical_path",
]
