"""Bridges from the existing stats silos into the MetricsRegistry.

Every `*Stats` surface the engine already maintains (NodeStats,
ExchangeStats, SchedulerStats, WireStats, GroupStats, CacheStats via
qcache snapshots, breaker stats, the kernel profile) exports here —
prestolint's `stats-not-exported` rule enforces that a surfaced Stats
class also reaches this module, so a new silo can't silently stay
invisible to `/v1/metrics`.

Naming scheme (docs/observability.md): `presto_<subsystem>_<what>` with
`_total` for counters and `_seconds`/`_bytes` units spelled out; labels
are low-cardinality only (cache name, breaker kernel, group name,
outcome) — never query ids or SQL.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Dict, Iterable, List, Optional

from .metrics import METRICS, Sample

if TYPE_CHECKING:  # annotations only — avoids exec/server import cycles
    from ..exec.qcache import CacheStats
    from ..exec.stats import NodeStats
    from ..plan.history import FeedbackStats
    from ..server.cluster import SchedulerStats
    from ..server.exchange import ExchangeStats
    from ..server.hier import HierExchangeStats
    from ..server.resource_groups import GroupStats
    from ..server.serde import WireStats

_defaults_lock = threading.Lock()
_defaults_done = False


def reset_defaults() -> None:
    global _defaults_done
    with _defaults_lock:
        _defaults_done = False


def ensure_default_exports() -> None:
    """Idempotent: declare the core series (stable scrape schema before
    the first increment) and register the process-global snapshot
    producers. Called by every scrape/collect."""
    global _defaults_done
    with _defaults_lock:
        if _defaults_done:
            return
        _defaults_done = True
    METRICS.declare_counter(
        "presto_queries_total", "Queries executed", {"outcome": "ok"}
    )
    METRICS.declare_counter(
        "presto_queries_total", labels={"outcome": "error"}
    )
    METRICS.declare_counter(
        "presto_exchange_pages_total", "Exchange pages pulled"
    )
    METRICS.declare_counter(
        "presto_exchange_wire_bytes_total", "Exchange bytes off the wire"
    )
    METRICS.declare_counter(
        "presto_exchange_hidden_seconds_total",
        "Exchange wire wall hidden behind device compute",
    )
    METRICS.declare_counter(
        "presto_hier_exchanges_total",
        "Output batches regrouped by the hierarchical exchange",
        {"role": "task"},
    )
    METRICS.declare_counter(
        "presto_wire_encode_seconds_total", "Page serialization wall"
    )
    METRICS.declare_counter(
        "presto_wire_decode_seconds_total", "Page deserialization wall"
    )
    METRICS.declare_counter(
        "presto_worker_tasks_total", "Worker tasks run", {"state": "FINISHED"}
    )
    METRICS.declare_counter(
        "presto_worker_tasks_total", labels={"state": "FAILED"}
    )
    METRICS.register_producer("qcache", _metrics_qcache_producer)
    METRICS.register_producer("breakers", _metrics_breaker_producer)
    METRICS.register_producer("kernel_profile", _metrics_kernel_producer)
    METRICS.register_producer("feedback", _metrics_feedback_producer)


# ---------------------------------------------------------------------------
# pull producers: process-global snapshot owners, evaluated at scrape
# ---------------------------------------------------------------------------


def export_cache_stats(cache: str, stats: "CacheStats") -> List[Sample]:
    """One qcache LRU's CacheStats as counter/gauge samples."""
    snap = stats.snapshot()
    label = (("cache", cache),)
    out: List[Sample] = []
    for field in ("hits", "misses", "stores", "evictions",
                  "invalidations", "patches"):
        out.append((
            f"presto_qcache_{field}_total", "counter", label,
            float(snap[field]),
        ))
    out.append((
        "presto_qcache_bytes", "gauge", label, float(snap["bytes"])
    ))
    return out


def _metrics_qcache_producer() -> List[Sample]:
    from ..exec.qcache import (
        HISTORY_CACHE, KERNEL_CACHE, PLAN_CACHE, RESULT_CACHE,
    )

    out: List[Sample] = []
    for name, cache in (
        ("plan", PLAN_CACHE), ("result", RESULT_CACHE),
        ("kernel", KERNEL_CACHE), ("history", HISTORY_CACHE),
    ):
        out.extend(export_cache_stats(name, cache.stats))
    return out


def export_feedback_stats(stats: "FeedbackStats") -> List[Sample]:
    """The adaptive-execution plane's FeedbackStats (plan/history.py) as
    `presto_feedback_*` samples: store traffic, estimate quality, and
    mid-query replans."""
    snap = stats.snapshot()
    out: List[Sample] = []
    for field in ("hits", "misses", "records", "invalidations",
                  "decays", "mispredictions", "replans"):
        out.append((
            f"presto_feedback_{field}_total", "counter", (),
            float(snap[field]),
        ))
    err = snap.get("mean_abs_rel_err")
    if err is not None:
        out.append((
            "presto_feedback_estimate_rel_error", "gauge", (), float(err)
        ))
    return out


def _metrics_feedback_producer() -> List[Sample]:
    from ..plan.history import HISTORY

    return export_feedback_stats(HISTORY.stats)


def _metrics_breaker_producer() -> List[Sample]:
    from ..exec.breaker import BREAKERS

    snap = BREAKERS.snapshot()
    open_count = 0
    out: List[Sample] = []
    for kernel, s in sorted(snap.items()):
        is_open = 1.0 if s.get("state") == "open" else 0.0
        open_count += int(is_open)
        label = (("kernel", kernel),)
        out.append(("presto_breaker_open", "gauge", label, is_open))
        out.append((
            "presto_breaker_failures_total", "counter", label,
            float(s.get("total_failures", 0)),
        ))
        out.append((
            "presto_breaker_successes_total", "counter", label,
            float(s.get("total_successes", 0)),
        ))
    # summary gauge is ALWAYS present so scrapers see the breaker plane
    # even before any kernel has tripped
    out.append((
        "presto_breakers_open_count", "gauge", (), float(open_count)
    ))
    return out


def _metrics_kernel_producer() -> List[Sample]:
    from .kernelprof import KERNEL_PROFILE

    snap = KERNEL_PROFILE.snapshot()
    return [
        ("presto_kernel_compiles_total", "counter", (),
         float(snap["compiles"])),
        ("presto_kernel_compile_seconds_total", "counter", (),
         snap["compile_s"]),
        ("presto_kernel_executions_total", "counter", (),
         float(snap["executions"])),
        ("presto_kernel_execute_seconds_total", "counter", (),
         snap["execute_s"]),
    ]


def export_group_stats(groups: Iterable["GroupStats"]) -> List[Sample]:
    out: List[Sample] = []
    for g in groups:
        label = (("group", g.name),)
        out.append((
            "presto_resource_group_running", "gauge", label,
            float(g.running),
        ))
        out.append((
            "presto_resource_group_queued", "gauge", label, float(g.queued)
        ))
        out.append((
            "presto_resource_group_cpu_used_seconds", "gauge", label,
            float(g.cpu_used_s),
        ))
    return out


def register_resource_groups(manager) -> None:
    """Scrape-time producer over the coordinator's resource-group tree
    (fixed key: a re-created QueryManager replaces, never accumulates)."""
    METRICS.register_producer(
        "resource_groups", lambda: export_group_stats(manager.stats())
    )


# ---------------------------------------------------------------------------
# push exporters: per-query / per-task folds at the silo's own fold point
# ---------------------------------------------------------------------------


def export_node_stats(by_node: Dict[int, "NodeStats"]) -> None:
    """Fold one resolved StatsCollector (EXPLAIN ANALYZE run) into the
    exec series."""
    calls = wall = rows = out_bytes = 0
    for s in by_node.values():
        calls += s.calls
        wall += s.wall_s
        rows += max(0, s.rows_out)
        out_bytes += s.out_bytes_total
    METRICS.counter("presto_exec_node_calls_total", calls,
                    help="Plan-node dispatches (EXPLAIN ANALYZE runs)")
    METRICS.counter("presto_exec_node_wall_seconds_total", wall)
    METRICS.counter("presto_exec_rows_total", rows)
    METRICS.counter("presto_exec_output_bytes_total", out_bytes)


def export_exchange_stats(pull: "ExchangeStats") -> None:
    """Fold one gather's pull-side accounting (each ExchangeStats lives
    for one gather and is folded exactly once, at _record_exchange)."""
    snap = pull.snapshot()
    METRICS.counter("presto_exchange_pages_total", snap.get("pages", 0))
    METRICS.counter(
        "presto_exchange_wire_bytes_total", snap.get("wire_bytes", 0)
    )
    METRICS.counter(
        "presto_exchange_responses_total", snap.get("responses", 0)
    )
    METRICS.counter(
        "presto_exchange_pull_seconds_total",
        (snap.get("pull_ms") or 0) / 1e3,
    )
    METRICS.counter(
        "presto_exchange_decode_seconds_total",
        (snap.get("decode_ms") or 0) / 1e3,
    )
    # overlap plane (hierarchical exchange): wire wall split into the
    # part the consumer actually waited for vs the part its device
    # compute hid behind prefetching pullers
    METRICS.counter(
        "presto_exchange_consumer_wait_seconds_total",
        (snap.get("consumer_wait_ms") or 0) / 1e3,
    )
    METRICS.counter(
        "presto_exchange_hidden_seconds_total",
        (snap.get("hidden_ms") or 0) / 1e3,
    )


def export_hier_stats(stats: "HierExchangeStats",
                      role: str = "task") -> None:
    """Fold one endpoint's hierarchical-exchange accounting into the
    metrics plane — called once when the endpoint retires. `role`
    labels the fold point ("task" = a worker's own producer regroup,
    "gather" = the coordinator's per-exchange fold over its producers'
    status payloads) so an in-process fleet sharing one registry never
    double-counts one series."""
    snap = stats.snapshot()
    label = {"role": role}
    METRICS.counter(
        "presto_hier_exchanges_total", snap.get("exchanges", 0), label,
        help="Output batches regrouped by the hierarchical exchange",
    )
    METRICS.counter(
        "presto_hier_collective_exchanges_total",
        snap.get("collective_exchanges", 0), label,
    )
    METRICS.counter("presto_hier_rows_total", snap.get("rows", 0), label)
    METRICS.counter(
        "presto_hier_collective_seconds_total",
        (snap.get("collective_ms") or 0) / 1e3, label,
    )
    METRICS.counter(
        "presto_hier_wire_pages_total", snap.get("wire_pages", 0), label
    )
    METRICS.counter(
        "presto_hier_ragged_pad_rows_total",
        snap.get("ragged_pad_rows", 0), label,
    )
    METRICS.counter(
        "presto_hier_fixed_pad_rows_total",
        snap.get("fixed_pad_rows", 0), label,
    )
    METRICS.counter(
        "presto_hier_fallbacks_total", snap.get("fallbacks", 0), label
    )


def export_wire_stats(role: str, stats: "WireStats") -> None:
    """Fold one endpoint's serde accounting (a task's output serializer
    or a pull client's decoder) — called once when the endpoint retires."""
    snap = stats.snapshot() if hasattr(stats, "snapshot") else {}
    label = {"role": role}
    METRICS.counter(
        "presto_wire_pages_total", snap.get("pages", 0), label
    )
    METRICS.counter(
        "presto_wire_bytes_total", snap.get("wire_bytes", 0), label
    )
    METRICS.counter(
        "presto_wire_encode_seconds_total",
        (snap.get("encode_ms") or 0) / 1e3, label,
    )
    METRICS.counter(
        "presto_wire_decode_seconds_total",
        (snap.get("decode_ms") or 0) / 1e3, label,
    )


def export_scheduler_stats(stats: "SchedulerStats") -> None:
    """Publish the scheduler's cumulative counters as gauges (the
    SchedulerStats object is itself cumulative; re-publishing is
    idempotent). Caller holds the scheduler lock."""
    import dataclasses

    for field, value in dataclasses.asdict(stats).items():
        if isinstance(value, (int, float)):
            METRICS.gauge(f"presto_scheduler_{field}", float(value))


def export_query(outcome: str, wall_s: float,
                 phase_ms: Optional[Dict[str, float]] = None) -> None:
    """One query completion (single-process or cluster execution layer)."""
    METRICS.counter(
        "presto_queries_total", 1, {"outcome": outcome},
        help="Queries executed",
    )
    METRICS.observe(
        "presto_query_seconds", wall_s, help="Query wall time"
    )
    for phase, ms in (phase_ms or {}).items():
        METRICS.observe(f"presto_query_phase_{phase}_seconds", ms / 1e3)
