"""SQL type system for the TPU-native engine.

Re-designed equivalent of the reference's type layer
(presto-spi/src/main/java/com/facebook/presto/spi/type/ — Type.java,
BigintType.java, DecimalType.java, VarcharType.java, ...). Instead of JVM
objects reading io.airlift.slice memory, every type maps onto a fixed-width
device array representation so relational kernels compile onto the TPU MXU/VPU:

  BIGINT     -> int64 (XLA emulates 64-bit on TPU; exact SQL semantics win)
  INTEGER    -> int32
  SMALLINT   -> int16
  TINYINT    -> int8
  DOUBLE     -> float64 on CPU oracle, float32/float64 selectable on TPU
  REAL       -> float32
  BOOLEAN    -> bool
  DATE       -> int32 days since 1970-01-01
  TIMESTAMP  -> int64 microseconds since epoch
  DECIMAL(p,s) (p<=18) -> int64 scaled integer (reference "short decimal",
               presto-spi/.../type/DecimalType.java + Decimals.java)
  VARCHAR/CHAR -> int32 dictionary codes over a host-side sorted dictionary
               (reference DictionaryBlock precedent,
               presto-spi/.../block/DictionaryBlock.java); sorted dictionaries
               make code order == string order so comparisons/sorts stay on
               device.

Nulls are carried as a separate validity mask at the Block level (page.py),
mirroring the reference's per-position isNull flags (spi/block/Block.java).
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """Base class for SQL types. Hashable, comparable, usable as static aux data."""

    name: ClassVar[str] = "unknown"

    @property
    def storage_dtype(self):
        raise NotImplementedError

    @property
    def is_orderable(self) -> bool:
        return True

    @property
    def is_comparable(self) -> bool:
        return True

    def display(self) -> str:
        return self.name

    def __str__(self) -> str:
        return self.display()

    # -- conversion helpers (host side) --
    def to_python(self, storage_value, dictionary=None):
        """Convert a storage scalar (numpy) to the natural Python value."""
        return storage_value.item() if hasattr(storage_value, "item") else storage_value


@dataclasses.dataclass(frozen=True)
class FixedWidthType(Type):
    pass


@dataclasses.dataclass(frozen=True)
class BigintType(FixedWidthType):
    name: ClassVar[str] = "bigint"

    @property
    def storage_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class IntegerType(FixedWidthType):
    name: ClassVar[str] = "integer"

    @property
    def storage_dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class SmallintType(FixedWidthType):
    name: ClassVar[str] = "smallint"

    @property
    def storage_dtype(self):
        return jnp.int16


@dataclasses.dataclass(frozen=True)
class TinyintType(FixedWidthType):
    name: ClassVar[str] = "tinyint"

    @property
    def storage_dtype(self):
        return jnp.int8


@dataclasses.dataclass(frozen=True)
class DoubleType(FixedWidthType):
    name: ClassVar[str] = "double"

    @property
    def storage_dtype(self):
        return jnp.float64


@dataclasses.dataclass(frozen=True)
class RealType(FixedWidthType):
    name: ClassVar[str] = "real"

    @property
    def storage_dtype(self):
        return jnp.float32


@dataclasses.dataclass(frozen=True)
class BooleanType(FixedWidthType):
    name: ClassVar[str] = "boolean"

    @property
    def storage_dtype(self):
        return jnp.bool_


@dataclasses.dataclass(frozen=True)
class DateType(FixedWidthType):
    """Days since 1970-01-01 in int32 (reference spi/type/DateType.java)."""

    name: ClassVar[str] = "date"

    @property
    def storage_dtype(self):
        return jnp.int32

    def to_python(self, storage_value, dictionary=None):
        days = int(storage_value)
        return (np.datetime64("1970-01-01") + np.timedelta64(days, "D")).astype(
            "datetime64[D]"
        )


@dataclasses.dataclass(frozen=True)
class TimestampType(FixedWidthType):
    """Microseconds since epoch in int64."""

    name: ClassVar[str] = "timestamp"

    @property
    def storage_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class DecimalType(FixedWidthType):
    """Decimal as scaled integers (reference DecimalType.java/Decimals.java).

    precision <= 18 ("short"): one int64 scaled by 10**scale.
    precision  > 18 ("long"):  TWO int64 lanes per row — block data has
    shape (capacity, 2), value = hi*2**32 + lo (ops/decimal128.py), the
    TPU-native stand-in for the reference's UnscaledDecimal128Arithmetic.
    """

    precision: int = 18
    scale: int = 0
    name: ClassVar[str] = "decimal"

    def __post_init__(self):
        if not (1 <= self.precision <= 38):
            raise ValueError(f"unsupported decimal precision {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"bad decimal scale {self.scale}")

    @property
    def is_long(self) -> bool:
        return self.precision > 18

    @property
    def storage_dtype(self):
        return jnp.int64

    @property
    def lanes(self) -> int:
        return 2 if self.is_long else 1

    def display(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def to_python(self, storage_value, dictionary=None):
        import decimal as _dec

        if self.is_long:
            hi, lo = (int(x) for x in storage_value)
            v = hi * (1 << 32) + lo
        else:
            v = int(storage_value)
        if self.scale == 0:
            return v
        return _dec.Decimal(v).scaleb(-self.scale)


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    """Dictionary-coded string type. Storage = int32 codes into a sorted
    host-side dictionary attached to the Block (page.py:Block.dictionary)."""

    max_length: Optional[int] = None
    name: ClassVar[str] = "varchar"

    @property
    def storage_dtype(self):
        return jnp.int32

    def display(self) -> str:
        if self.max_length is None:
            return "varchar"
        return f"varchar({self.max_length})"

    def to_python(self, storage_value, dictionary=None):
        code = int(storage_value)
        if dictionary is None:
            return code
        return dictionary[code]


@dataclasses.dataclass(frozen=True)
class CharType(VarcharType):
    name: ClassVar[str] = "char"

    def display(self) -> str:
        return f"char({self.max_length})" if self.max_length else "char"


@dataclasses.dataclass(frozen=True)
class IntervalDayType(FixedWidthType):
    """INTERVAL DAY TO SECOND, stored as int64 days (sub-day resolution is a
    later milestone; TPC-H uses whole-day/month/year intervals only)."""

    name: ClassVar[str] = "interval day to second"

    @property
    def storage_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class IntervalYearMonthType(FixedWidthType):
    """INTERVAL YEAR TO MONTH, stored as int64 months."""

    name: ClassVar[str] = "interval year to month"

    @property
    def storage_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class UnknownType(Type):
    """Type of a bare NULL literal (reference spi/type/UnknownType)."""

    name: ClassVar[str] = "unknown"

    @property
    def storage_dtype(self):
        return jnp.bool_


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(element) (reference spi/type/ArrayType.java). TPU-first
    representation: arrays exist during EXPRESSION evaluation only — a
    Val whose data is (capacity, width) with per-row lengths (width is the
    trace-static max; -1 length marks a NULL array). They are consumed by
    UNNEST / array functions before page materialization; array-typed
    table columns are not supported."""

    element: Type = None  # type: ignore[assignment]
    # sketch marker: "hll" tags approx_set's register arrays so
    # cardinality() reads the HLL estimate instead of the lane count
    # (the reference has a distinct HYPERLOGLOG type; here the sketch
    # rides ARRAY(TINYINT) with this annotation)
    sketch: Optional[str] = None
    name: ClassVar[str] = "array"

    @property
    def storage_dtype(self):
        return self.element.storage_dtype

    def display(self) -> str:
        return f"array({self.element})"

    def to_python(self, storage_value, dictionary=None):
        raise TypeError(
            "array values cannot be materialized into result rows; "
            "UNNEST or aggregate them first"
        )


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """MAP(key, value) (reference spi/type/MapType.java). Like arrays,
    maps live in expression values and collection-aggregate RESULT blocks
    (values in data + lengths, keys in a companion key block); map-typed
    table columns are not supported."""

    key: Type = None  # type: ignore[assignment]
    value: Type = None  # type: ignore[assignment]
    name: ClassVar[str] = "map"

    @property
    def storage_dtype(self):
        return self.value.storage_dtype

    def display(self) -> str:
        return f"map({self.key}, {self.value})"

    def to_python(self, storage_value, dictionary=None):
        raise TypeError(
            "map rows are decoded by Page.to_pylist via the key block"
        )


# Singletons
BIGINT = BigintType()
INTEGER = IntegerType()
SMALLINT = SmallintType()
TINYINT = TinyintType()
DOUBLE = DoubleType()
REAL = RealType()
BOOLEAN = BooleanType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARCHAR = VarcharType()
UNKNOWN = UnknownType()
INTERVAL_DAY = IntervalDayType()
INTERVAL_YEAR_MONTH = IntervalYearMonthType()


def decimal(precision: int, scale: int) -> DecimalType:
    return DecimalType(precision=precision, scale=scale)


INTEGRAL_TYPES = (BigintType, IntegerType, SmallintType, TinyintType)
FLOAT_TYPES = (DoubleType, RealType)


def is_integral(t: Type) -> bool:
    return isinstance(t, INTEGRAL_TYPES)


def is_floating(t: Type) -> bool:
    return isinstance(t, FLOAT_TYPES)


def is_numeric(t: Type) -> bool:
    return is_integral(t) or is_floating(t) or isinstance(t, DecimalType)


def is_string(t: Type) -> bool:
    return isinstance(t, VarcharType)


def parse_type(text: str) -> Type:
    """Parse a type name as it appears in SQL (CAST targets, DDL)."""
    s = text.strip().lower()
    simple = {
        "bigint": BIGINT,
        "integer": INTEGER,
        "int": INTEGER,
        "smallint": SMALLINT,
        "tinyint": TINYINT,
        "double": DOUBLE,
        "double precision": DOUBLE,
        "real": REAL,
        "float": REAL,
        "boolean": BOOLEAN,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varchar": VARCHAR,
        "unknown": UNKNOWN,
        "interval day to second": INTERVAL_DAY,
        "interval year to month": INTERVAL_YEAR_MONTH,
    }
    if s in simple:
        return simple[s]
    if s.startswith("decimal"):
        inner = s[len("decimal") :].strip()
        if inner.startswith("(") and inner.endswith(")"):
            parts = [p.strip() for p in inner[1:-1].split(",")]
            p = int(parts[0])
            sc = int(parts[1]) if len(parts) > 1 else 0
            return decimal(p, sc)
        return decimal(18, 0)
    if s.startswith("varchar(") and s.endswith(")"):
        return VarcharType(max_length=int(s[len("varchar(") : -1]))
    if s.startswith("char(") and s.endswith(")"):
        return CharType(max_length=int(s[len("char(") : -1]))
    if s.startswith("array(") and s.endswith(")"):
        return ArrayType(parse_type(s[len("array(") : -1]))
    if s.startswith("map(") and s.endswith(")"):
        inner = s[len("map(") : -1]
        depth = 0
        for i, ch in enumerate(inner):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                return MapType(
                    parse_type(inner[:i]), parse_type(inner[i + 1 :])
                )
        raise ValueError(f"malformed map type: {text!r}")
    raise ValueError(f"unknown type: {text!r}")


def common_super_type(a: Type, b: Type) -> Type:
    """Implicit coercion lattice (reference metadata/TypeCoercion — simplified)."""
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    rank = {TinyintType: 0, SmallintType: 1, IntegerType: 2, BigintType: 3}
    ta, tb = type(a), type(b)
    if ta in rank and tb in rank:
        return a if rank[ta] >= rank[tb] else b
    if is_floating(a) and is_floating(b):
        return DOUBLE
    if (is_floating(a) and is_numeric(b)) or (is_floating(b) and is_numeric(a)):
        return DOUBLE
    if isinstance(a, DecimalType) and is_integral(b):
        return DecimalType(38 if a.is_long else 18, a.scale)
    if isinstance(b, DecimalType) and is_integral(a):
        return DecimalType(38 if b.is_long else 18, b.scale)
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        return DecimalType(38 if (a.is_long or b.is_long) else 18, scale)
    if is_string(a) and is_string(b):
        return VARCHAR
    raise TypeError(f"no common type for {a} and {b}")
