"""presto_tpu — a TPU-native distributed SQL query engine.

A ground-up rebuild of the capabilities of kaka11chen/presto (Presto
0.216-SNAPSHOT, coordinator/worker MPP SQL engine) designed for TPU hardware:
columnar pages live in HBM as JAX arrays, relational operators are XLA/Pallas
kernels, repartitioning is jax.lax.all_to_all over the ICI mesh, and the
host-side control plane reproduces the coordinator/worker semantics.

Layer map (mirrors SURVEY.md §1):
  sql/        parser, analyzer, logical planner, optimizer   (L4)
  plan/       plan nodes, fragmenter, distribution           (L4)
  expr/       row expressions traced to fused jax fns        (L7 codegen)
  page.py     columnar Page/Block device representation      (L7 data plane)
  ops/        relational kernels (filter, agg, join, sort)   (L6 operators)
  exec/       driver/pipeline runner, task execution         (L6)
  parallel/   mesh, shardings, all_to_all exchange           (L8)
  connectors/ tpch generator, memory tables                  (L9/L10)
  server/     coordinator/worker control plane               (L2/L3/L11)
"""

import jax

# SQL semantics need 64-bit ints (BIGINT, short DECIMAL) and doubles. XLA:TPU
# emulates 64-bit with int32 pairs; exactness beats the emulation cost for the
# key/decimal paths, and hot float math stays in 32-bit where the planner says
# it's safe.
jax.config.update("jax_enable_x64", True)

# PRESTO_TPU_COMPILE_CACHE_DIR: persistent XLA compilation cache so worker
# restarts warm-start their executables (exec/qcache.py). Configured at
# import — before any compile can latch the cache uninitialized — and a
# pure config update, so no backend is touched here.
from .exec.qcache import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

from . import types  # noqa: E402
from .page import Block, Page  # noqa: E402

__version__ = "0.1.0"
__all__ = ["types", "Block", "Page"]
